"""Systematic concurrency harness.

The reference leans on Go's race detector in CI (SURVEY §5); the
equivalent discipline here is targeted interleaving stress: hammer
every shared structure from many threads while mutating the state it
guards, and assert invariants — every caller gets a correct answer,
no exception escapes, nothing deadlocks, resources drain on close.
"""
import threading
import time

import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

OK, NOT_FOUND, PERMISSION_DENIED = 0, 5, 7


def _store(n_extra=0):
    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": PERMISSION_DENIED}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "denyadmin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    for i in range(n_extra):
        s.set(("rule", "istio-system", f"r{i}"), {
            "match": f'request.path.startsWith("/x{i}/")',
            "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    return s


def test_checks_race_config_swaps():
    """Checks from many threads while the config churns: every caller
    must get a verdict consistent with SOME published snapshot (the
    deny rule is never removed, so /admin must always deny)."""
    store = _store()
    srv = RuntimeServer(store, ServerArgs(batch_window_s=0.001,
                                          max_batch=32, buckets=(32,)))
    failures: list = []
    stop = threading.Event()

    def checker(tid):
        i = 0
        while not stop.is_set():
            r = srv.check(bag_from_mapping(
                {"request.path": f"/admin/{tid}/{i}"}))
            if r.status_code != PERMISSION_DENIED:
                failures.append(("admin-not-denied", r.status_code))
            r2 = srv.check(bag_from_mapping(
                {"request.path": f"/ok/{tid}/{i}"}))
            if r2.status_code not in (OK, PERMISSION_DENIED):
                # /ok may hit a transient /x{i}/ rule only if the path
                # matched — it can't, so OK is the only legal verdict
                failures.append(("ok-bad-status", r2.status_code))
            i += 1

    def swapper():
        gen = 0
        while not stop.is_set():
            store.set(("rule", "istio-system", "churn"), {
                "match": f'request.path.startsWith("/churn{gen}/")',
                "actions": [{"handler": "denyall",
                             "instances": ["nothing"]}]})
            gen += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=checker, args=(t,), daemon=True)
               for t in range(6)] + \
              [threading.Thread(target=swapper, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "thread wedged"
    srv.close()
    assert not failures, failures[:5]


def test_close_races_inflight_checks():
    """close() while requests are in flight: every submitted future
    must resolve (result or error) — callers must never hang."""
    for _ in range(3):
        store = _store()
        srv = RuntimeServer(store, ServerArgs(batch_window_s=0.005,
                                              max_batch=64, buckets=(64,)))
        resolved = []
        errors = []

        def caller(i):
            try:
                srv.check(bag_from_mapping({"request.path": f"/p/{i}"}))
                resolved.append(i)
            except Exception:
                errors.append(i)

        threads = [threading.Thread(target=caller, args=(i,), daemon=True)
                   for i in range(24)]
        for t in threads:
            t.start()
        time.sleep(0.002)
        srv.close()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "caller hung across close()"
        assert len(resolved) + len(errors) == 24


def test_quota_exactness_under_concurrency():
    """memquota must never over-grant across concurrent callers."""
    from istio_tpu.adapters.registry import adapter_registry, load_inventory
    from istio_tpu.adapters.sdk import Env, QuotaArgs
    load_inventory()
    info = adapter_registry.get("memquota")
    builder = info.builder({"quotas": [{"name": "q", "max_amount": 50,
                                        "valid_duration_s": 60.0}]},
                           Env("test"))
    assert not builder.validate()
    h = builder.build()
    granted = []
    barrier = threading.Barrier(8)

    def taker():
        barrier.wait()
        got = 0
        for _ in range(25):
            r = h.handle_quota("quota", {"name": "q", "dimensions": {}},
                               QuotaArgs(quota_amount=1,
                                         best_effort=False))
            got += r.granted_amount
        granted.append(got)

    threads = [threading.Thread(target=taker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    h.close()
    assert sum(granted) == 50, f"granted {sum(granted)} of 50"


def test_device_quota_pool_exactness_under_concurrency():
    """The device-backed pool (runtime/device_quota.py) must never
    over-grant across concurrent callers hammering one cell — batched
    scatter-add allocation included. Mirrors the host memquota
    invariant above."""
    from istio_tpu.adapters.sdk import QuotaArgs
    from istio_tpu.runtime.device_quota import DeviceQuotaPool

    pool = DeviceQuotaPool({"q": {"name": "q", "max_amount": 50}},
                           n_buckets=32, batch_window_s=0.001,
                           max_batch=64)
    try:
        granted = []
        barrier = threading.Barrier(8)

        def taker():
            barrier.wait()
            got = 0
            futs = [pool.alloc("q", {"name": "q", "dimensions": {}},
                               QuotaArgs(quota_amount=1,
                                         best_effort=False))
                    for _ in range(25)]
            for f in futs:
                got += f.result(timeout=30).granted_amount
            granted.append(got)

        threads = [threading.Thread(target=taker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sum(granted) == 50, f"granted {sum(granted)} of 50"
    finally:
        pool.close()


def test_device_quota_pool_close_races_allocs():
    """close() during a storm: every future resolves (grant or
    UNAVAILABLE), none hangs."""
    from istio_tpu.adapters.sdk import QuotaArgs
    from istio_tpu.runtime.device_quota import DeviceQuotaPool

    pool = DeviceQuotaPool({"q": {"name": "q", "max_amount": 1 << 20}},
                           n_buckets=64, batch_window_s=0.001,
                           max_batch=32)
    futs = []
    stop = threading.Event()

    def feeder():
        i = 0
        while not stop.is_set():
            futs.append(pool.alloc(
                "q", {"name": "q", "dimensions": {"k": str(i % 16)}},
                QuotaArgs(quota_amount=1)))
            i += 1

    t = threading.Thread(target=feeder)
    t.start()
    time.sleep(0.2)
    pool.close()
    stop.set()
    t.join(timeout=10)
    for f in futs:
        r = f.result(timeout=10)   # resolves — never hangs
        assert r.status_code in (0, 14)


def test_batch_check_races_config_swaps():
    """BatchCheck RPCs (the shim protocol) from several threads while
    the config churns: every per-item verdict must be consistent with
    SOME published snapshot, like the unary race above."""
    import pytest
    pytest.importorskip("grpc")
    from istio_tpu.api import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer

    store = _store()
    srv = RuntimeServer(store, ServerArgs(batch_window_s=0.001,
                                          max_batch=32, buckets=(32,)))
    g = MixerGrpcServer(srv)
    port = g.start()
    failures: list = []
    stop = threading.Event()

    def checker(tid):
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        i = 0
        try:
            while not stop.is_set():
                resps = client.batch_check(
                    [{"request.path": f"/admin/{tid}/{i}/{j}"}
                     for j in range(5)] +
                    [{"request.path": f"/ok/{tid}/{i}/{j}"}
                     for j in range(5)])
                codes = [r.precondition.status.code for r in resps]
                if codes[:5] != [PERMISSION_DENIED] * 5:
                    failures.append(("admin-not-denied", codes[:5]))
                if any(c not in (OK, PERMISSION_DENIED)
                       for c in codes[5:]):
                    failures.append(("ok-bad-status", codes[5:]))
                i += 1
        finally:
            client.close()

    def swapper():
        gen = 0
        while not stop.is_set():
            store.set(("rule", "istio-system", "churn"), {
                "match": f'request.path.startsWith("/churn{gen}/")',
                "actions": [{"handler": "denyall",
                             "instances": ["nothing"]}]})
            gen += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=checker, args=(t,), daemon=True)
               for t in range(4)] + \
              [threading.Thread(target=swapper, daemon=True)]
    try:
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=15)
            assert not t.is_alive(), "thread wedged"
        assert not failures, failures[:5]
    finally:
        stop.set()
        g.stop()
        srv.close()


def test_rolling_pool_never_overgrants_across_window_rolls():
    """Concurrent unit allocs against a live ROLLING window while the
    clock advances: the safety invariant is that within any window,
    total granted never exceeds max_amount + (reclaimed slots). With
    the clock frozen per phase, each phase must grant exactly the
    reclaimed budget."""
    from istio_tpu.adapters.sdk import QuotaArgs
    from istio_tpu.runtime.device_quota import DeviceQuotaPool

    class Clock:
        def __init__(self):
            self.t = 50.0

        def __call__(self):
            return self.t

    clock = Clock()
    pool = DeviceQuotaPool(
        {"q": {"name": "q", "max_amount": 40,
               "valid_duration_s": 10.0}},
        n_buckets=8, batch_window_s=0.001, max_batch=64, clock=clock)
    try:
        def storm(n_threads=6, per_thread=20):
            granted = []
            barrier = threading.Barrier(n_threads)

            def taker():
                barrier.wait()
                futs = [pool.alloc("q", {"name": "q", "dimensions": {}},
                                   QuotaArgs(quota_amount=1,
                                             best_effort=True))
                        for _ in range(per_thread)]
                granted.append(sum(
                    f.result(timeout=30).granted_amount for f in futs))

            ts = [threading.Thread(target=taker)
                  for _ in range(n_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
                assert not t.is_alive(), "taker wedged"
            return sum(granted)

        assert storm() == 40          # window fills exactly once
        assert storm() == 0           # same ticks: nothing reclaimed
        clock.t += 5.0                # half the window rolls out...
        assert storm() == 0           # ...but all 40 were consumed at
        #                               the same tick — still live
        clock.t += 6.0                # now the consuming tick expired
        assert storm() == 40
    finally:
        pool.close()


def test_batcher_never_abandons_futures_on_prep_failure(monkeypatch):
    """A failure in batch PREP (outside the run_batch call — e.g. the
    tracing span construction) must resolve every future with the
    exception, never leave callers hanging (r4: a NameError in the
    span line hung every request of its batch)."""
    import pytest

    from istio_tpu.runtime.batcher import CheckBatcher
    from istio_tpu.utils import tracing

    b = CheckBatcher(lambda bags: [1] * len(bags), window_s=0.001,
                     max_batch=4)

    def boom():
        raise RuntimeError("span construction failed")

    monkeypatch.setattr(tracing, "get_tracer", boom)
    try:
        fut = b.submit(bag_from_mapping({"request.path": "/x"}))
        with pytest.raises(RuntimeError, match="span construction"):
            fut.result(timeout=15)
    finally:
        monkeypatch.undo()
        b.close()


def test_batcher_holds_batches_while_transport_busy():
    """Occupancy-adaptive window (VERDICT r4 item 6): while a device
    trip is in flight, arriving requests accumulate instead of
    dispatching tiny trips behind a busy serialized transport; an idle
    transport still dispatches after the fixed window (light-load
    latency stays one trip)."""
    import time as _time

    from istio_tpu.runtime.batcher import CheckBatcher, PadBag

    sizes = []
    lock = threading.Lock()

    def run_batch(bags):
        with lock:   # count REAL rows (the batcher pads to buckets)
            sizes.append(sum(1 for x in bags
                             if not isinstance(x, PadBag)))
        _time.sleep(0.12)          # a slow (tunnel-like) trip
        return ["ok"] * len(bags)

    b = CheckBatcher(run_batch, window_s=0.002, max_batch=64,
                     pipeline=1, buckets=(64,))
    try:
        futs = [b.submit(object())]
        _time.sleep(0.02)          # first trip departs near-empty
        # 30 requests arrive while that trip is in flight: they must
        # coalesce into few fat batches, not 30 tiny trips
        for _ in range(30):
            futs.append(b.submit(object()))
            _time.sleep(0.002)
        for f in futs:
            assert f.result(timeout=30) == "ok"
    finally:
        b.close()
    assert sizes[0] <= 2, sizes
    # the 30 busy-period arrivals ride at most a handful of batches
    assert len(sizes) <= 6, sizes
    assert max(sizes) >= 10, sizes


def test_store_watch_delivery_under_write_storm():
    """Concurrent writers + a watcher: the watcher must observe a
    coherent final state once writes quiesce (no lost updates)."""
    store = _store()
    seen = []
    store.watch(lambda events: seen.extend(events))

    def writer(tid):
        for i in range(30):
            store.set(("rule", "ns", f"w{tid}-{i}"), {
                "match": "", "actions": []})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    deadline = time.time() + 5
    while time.time() < deadline:
        written = {(e.key[1], e.key[2]) for e in seen
                   if e.key[1] == "ns"}
        if len(written) == 120:
            break
        time.sleep(0.02)
    assert len([k for k in store.list("rule") if k[1] == "ns"]) == 120
    # the watcher must have OBSERVED every write, not just the store
    assert len({(e.key[1], e.key[2]) for e in seen
                if e.key[1] == "ns"}) == 120


def test_kube_informer_churn_consistency():
    """Pod informer index vs cluster state after concurrent add/delete
    churn: indexes must converge exactly to the surviving pods."""
    from istio_tpu.adapters.kubernetesenv import InformerPodSource
    from istio_tpu.kube.fake import FakeKubeCluster

    cluster = FakeKubeCluster()
    src = InformerPodSource(cluster)

    def churner(tid):
        for i in range(40):
            name = f"pod-{tid}-{i}"
            cluster.apply({"kind": "Pod",
                           "metadata": {"name": name, "namespace": "d"},
                           "status": {"podIP": f"10.{tid}.0.{i}"}})
            if i % 3 == 0:
                cluster.delete("Pod", "d", name)

    threads = [threading.Thread(target=churner, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    expected = {f"{p['metadata']['name']}.d"
                for p in cluster.list("Pod")}
    assert set(src._pods) == expected
    src.close()


def test_cancelled_future_never_poisons_batch():
    """An aio client disconnect cancels its batcher future mid-batch;
    batch-mates must still resolve (set_result on a cancelled future
    raises InvalidStateError and previously aborted distribution)."""
    from istio_tpu.runtime.batcher import CheckBatcher

    release = threading.Event()

    def run_batch(bags):
        release.wait(5)
        return ["ok"] * len(bags)

    b = CheckBatcher(run_batch, window_s=0.2, max_batch=8, buckets=(8,))
    try:
        futs = [b.submit(object()) for _ in range(4)]
        futs[1].cancel()
        release.set()
        for i, f in enumerate(futs):
            if i == 1:
                assert f.cancelled()
            else:
                assert f.result(timeout=10) == "ok"
    finally:
        b.close()


def test_wire_dedup_replay_across_clients_and_windows():
    """VERDICT r4 item 10: CONCURRENT gRPC clients firing the SAME
    deduplication_id must replay ONE grant, never double-consume —
    ids landing in one device batch window, ids racing the original's
    flush, and ids re-sent after the window flushed all take the
    replay path (memquota.go:259 buildWithDedup semantics, proven at
    the real wire against the device quota pool)."""
    pytest.importorskip("grpc")
    from concurrent.futures import ThreadPoolExecutor

    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer

    s = MemStore()
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 10}]}})   # exact counter
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("rule", "istio-system", "quota-all"), {
        "match": "",
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001,
                                      max_batch=32, buckets=(32,)))
    g = MixerGrpcServer(srv)
    port = g.start()
    values = {"source.user": "alice", "request.path": "/ok"}
    try:
        assert srv.controller.dispatcher.fused is not None

        def one(dedup_id):
            # own channel per call: real concurrent client sockets
            cli = MixerClient(f"127.0.0.1:{port}",
                              enable_check_cache=False)
            try:
                resp = cli.check(values, quotas={"rq": 5},
                                 dedup_id=dedup_id)
                assert resp.precondition.status.code == OK
                return resp.quotas["rq"].granted_amount
            finally:
                cli.close()

        # wave 1: 8 clients, one dedup id, one batch window — exactly
        # ONE 5-unit consumption, every caller sees the grant replayed
        with ThreadPoolExecutor(max_workers=8) as pool:
            wave1 = list(pool.map(one, ["X"] * 8))
        assert wave1 == [5] * 8

        # wave 2 (after the window flushed): the SAME id replays from
        # the dedup cache without consuming
        time.sleep(0.2)
        with ThreadPoolExecutor(max_workers=4) as pool:
            wave2 = list(pool.map(one, ["X"] * 4))
        assert wave2 == [5] * 4

        # the proof of single consumption: 5 of 10 remain for a FRESH
        # id; after that the counter is exhausted
        assert one("Y") == 5
        assert one("Z") == 0
    finally:
        g.stop()
        srv.close()
