"""Tier-1 hook for scripts/report_smoke.py: the CI gate that the
telemetry ingestion plane stays a measurement — Report served
end-to-end over real HTTP (native wire when the toolchain builds,
python gRPC otherwise) conserves records EXACTLY (accepted ==
adapter-exported + typed-rejected), all six pipeline stage histograms
record observations, /debug/report serves and agrees with the live
counters, and a bounded coalescer under overflow sheds typed
RESOURCE_EXHAUSTED at the wire without dropping a record silently.
Runs main() in-process (the introspect_smoke pattern: a subprocess
would pay a second jax import for no extra coverage; the script stays
runnable standalone under JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "report_smoke.py")
    spec = importlib.util.spec_from_file_location("report_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_report_smoke_main():
    mod = _load()
    try:
        rc = mod.main(n_rules=10, n_rpcs=3, records_per_rpc=6)
    finally:
        sys.modules.pop("report_smoke", None)
    assert rc == 0
