"""ISSUE 6 unit coverage: the roofline model's shape-exactness, the
fused gather–compare fast path's oracle parity + compiled-away legacy
stage, bit-packed bank/mask round-trips, latency-tier byte-plane
specialization (identical verdicts across tier shapes), and the
in-step quota prewarm wiring (ADVICE r5: defined-but-never-called)."""
import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.compiler import roofline
from istio_tpu.compiler.layout import Tensorizer
from istio_tpu.compiler.ruleset import Rule, compile_ruleset
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.ops import bytes_ops
from istio_tpu.ops.bytes_ops import pack_bits
from istio_tpu.ops.regex_dfa import compile_regex, dfa_matches_host
from istio_tpu.testing import workloads
from istio_tpu.testing.corpus import CORPUS_MANIFEST

FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)


# ---------------------------------------------------------------------------
# roofline model
# ---------------------------------------------------------------------------

def test_h2d_component_matches_tensorized_batch_exactly():
    engine = workloads.make_engine(n_rules=48, with_quota=True,
                                   jit=False)
    b = 32
    model = roofline.model_check_step(engine, b)
    ab = engine.tensorizer.tensorize(workloads.make_bags(b))
    actual = sum(int(np.asarray(a).nbytes) for a in (
        ab.ids, ab.present, ab.map_present, ab.str_bytes,
        ab.str_lens, ab.hash_ids))
    assert model.component("h2d_batch").bytes == actual


def test_index_tensor_bytes_match_live_params():
    engine = workloads.make_engine(n_rules=48, with_quota=False,
                                   jit=False)
    b = 16
    model = roofline.model_check_step(engine, b)
    params = engine.ruleset.params
    g = engine.ruleset.geometry
    want = sum(int(np.asarray(params[k]).nbytes)
               for k in ("conj_m_idx", "conj_n_idx"))
    got = model.component("match_rules").bytes \
        - b * g["n_rows"] * (2 * g["k_max"] + 3)
    assert got == want


def test_report_names_binding_resource():
    engine = workloads.make_engine(n_rules=32, with_quota=False,
                                   jit=False)
    model = roofline.model_check_step(engine, 64)
    # a step wall at ~the model's own roof time → device-bound label
    peaks = {"hbm_gbps": 1.0, "mxu_tops": 1.0, "label": "unit"}
    hbm_s = model.bytes_per_step / 1e9
    rep = model.report(hbm_s * 2, peaks)
    assert rep["bound"] in ("hbm", "mxu")
    assert 0 < rep["fraction_of_roof"] <= 1.0
    # a wall 1000× the roof → host-bound (dispatch/transport)
    rep = model.report(max(hbm_s, model.mxu_ops_per_step / 1e12)
                       * 1000, peaks)
    assert rep["bound"] == "host"


def test_bench_fields_prefixed_and_fail_soft():
    engine = workloads.make_engine(n_rules=24, with_quota=False,
                                   jit=False)
    out = roofline.bench_fields(engine, 32, 1e-3, "zzz_")
    assert "zzz_fraction_of_roof" in out and "zzz_bound" in out
    # fail-soft: garbage engine yields an error field, never a raise
    out = roofline.bench_fields(object(), 32, 1e-3, "bad_")
    assert "bad_roofline_error" in out


# ---------------------------------------------------------------------------
# fused gather–compare fast path
# ---------------------------------------------------------------------------

EQ_RULES = [
    Rule(name="r0", match='as == "abc"'),
    Rule(name="r1", match='as != "xyz" && ab == true'),
    Rule(name="r2", match='ai == 42 || (as == "q" && ad == 1.5)'),
    Rule(name="r3", match=""),
]
MIXED_RULES = EQ_RULES + [
    Rule(name="r4", match='as.startsWith("ab")'),
    Rule(name="r5", match='as == as2 && ai == 7'),
]
INPUTS = [
    {"as": "abc", "ab": True, "ai": 42, "ad": 1.5, "as2": "abc"},
    {"as": "xyz", "ab": False, "ai": 7, "as2": "zzz"},
    {"as": "q", "ad": 1.5, "ai": 7, "as2": "q"},
    {"ab": True},
    {},
]


def _oracle(text, bag):
    try:
        v = bool(OracleProgram(text or "true", FINDER).evaluate(bag))
        return (v, not v, False)
    except EvalError:
        return (False, False, True)


def _run(prog, bags):
    tz = Tensorizer(prog.layout, prog.interner)
    m, n, e = prog(tz.tensorize(bags))
    return np.asarray(m), np.asarray(n), np.asarray(e)


def test_pure_eq_ruleset_compiles_away_legacy_stage():
    prog = compile_ruleset(EQ_RULES, FINDER)
    g = prog.geometry
    assert g["n_fused_conjs"] > 0
    assert g["n_legacy_conjs"] == 0
    assert not g["use_legacy"]
    bags = [bag_from_mapping(i) for i in INPUTS]
    m, n, e = _run(prog, bags)
    for ridx, rule in enumerate(EQ_RULES):
        for b, inp in enumerate(INPUTS):
            want = _oracle(rule.match, bag_from_mapping(inp))
            got = (bool(m[b, ridx]), bool(n[b, ridx]),
                   bool(e[b, ridx]))
            assert got == want, (rule.match, inp, got, want)


def test_mixed_ruleset_splits_conjunctions_and_matches_oracle():
    prog = compile_ruleset(MIXED_RULES, FINDER)
    g = prog.geometry
    assert g["n_fused_conjs"] > 0
    assert g["n_legacy_conjs"] > 0 and g["use_legacy"]
    assert g["n_fused_conjs"] + g["n_legacy_conjs"] == g["n_conjs"]
    bags = [bag_from_mapping(i) for i in INPUTS]
    m, n, e = _run(prog, bags)
    for ridx, rule in enumerate(MIXED_RULES):
        if ridx in prog.host_fallback:
            continue
        for b, inp in enumerate(INPUTS):
            want = _oracle(rule.match, bag_from_mapping(inp))
            got = (bool(m[b, ridx]), bool(n[b, ridx]),
                   bool(e[b, ridx]))
            assert got == want, (rule.match, inp, got, want)


# ---------------------------------------------------------------------------
# bit-packed lanes
# ---------------------------------------------------------------------------

def test_pack_unpack_bits_roundtrip():
    rng = np.random.default_rng(7)
    for shape in ((5,), (3, 37), (2, 4, 65), (1, 32), (6, 1)):
        a = rng.random(shape) < 0.3
        packed = pack_bits(a)
        assert packed.dtype == np.uint32
        assert packed.shape[-1] == (shape[-1] + 31) // 32
        back = np.asarray(bytes_ops.unpack_bits(packed, shape[-1]))
        np.testing.assert_array_equal(back, a)


def test_bitpacked_regex_list_bank_oracle_parity():
    """REGEX list actions drive the engine's packed (bit-lane) DFA
    banks; deny verdicts must match host automaton membership for
    whitelist AND blacklist polarity over a corpus of subjects."""
    from istio_tpu.models.policy_engine import (ListEntrySpec,
                                                PolicyEngine)

    patterns = [r"^/api/v[0-9]+/", r"\.internal$", r"(foo|bar)baz",
                r"^/healthz$"]
    rules = [Rule(name="white", match=""), Rule(name="black", match="")]
    engine = PolicyEngine(
        rules=rules, finder=FINDER,
        lists=[ListEntrySpec(rule=0, value_attr="as",
                             entries=patterns, blacklist=False,
                             entry_type="REGEX"),
               ListEntrySpec(rule=1, value_attr="as",
                             entries=patterns, blacklist=True,
                             entry_type="REGEX")])
    subjects = ["/api/v3/items", "db.internal", "foobaz", "/healthz",
                "/api/vx/items", "internal.db", "bazfoo", "", "zzz"]
    bags = [bag_from_mapping({"as": s}) for s in subjects]
    batch = engine.tensorizer.tensorize(bags)
    verdict = engine.check(batch, np.zeros(len(bags), np.int32))
    status = np.asarray(verdict.status)
    dfas = [compile_regex(p) for p in patterns]
    for i, s in enumerate(subjects):
        member = any(dfa_matches_host(d, s.encode()) for d in dfas)
        # blacklist hit → PERMISSION_DENIED(7) at rule 1; whitelist
        # miss → NOT_FOUND(5) at rule 0 (lowest rule index wins)
        want = 7 if member else 5
        assert int(status[i]) == want, (s, member, int(status[i]))


# ---------------------------------------------------------------------------
# latency-tier byte-plane specialization
# ---------------------------------------------------------------------------

def _tier_plan():
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.runtime.fused import build_fused_plan

    store = workloads.make_store(48, with_regex=True)
    snap = SnapshotBuilder(
        default_manifest=workloads.MESH_MANIFEST).build(store)
    return build_fused_plan(snap)


def test_str_tier_narrowing_identical_verdicts():
    """Bucket-specialization satellite: the SAME batch served through
    the narrowed latency tier and the full-width worst case must
    produce bit-identical packed verdicts."""
    plan = _tier_plan()
    lay = plan.engine.ruleset.layout
    if len(plan.str_tiers) < 2:
        pytest.skip("layout has no multi-tier byte planes")
    bags = workloads.make_bags(16, seed=3)
    batch = plan.engine.tensorizer.tensorize(bags)
    assert int(batch.str_lens.max()) <= plan.str_tiers[0], \
        "workload strings must fit the small tier for this test"
    ns = np.zeros(16, np.int32)
    narrowed = plan.narrow_batch(batch)
    assert narrowed.str_bytes.shape[2] == plan.str_tiers[0]
    assert narrowed.str_bytes.shape[2] < lay.max_str_len
    packed_narrow = plan.packed_check(batch, ns, observe=False)
    # force the full-width shape by disabling the tiers
    plan.str_tiers = (lay.max_str_len,)
    packed_full = plan.packed_check(batch, ns, observe=False)
    np.testing.assert_array_equal(packed_narrow, packed_full)


def test_str_tier_gated_off_by_long_byte_constant():
    """A compiled byte CONSTANT longer than the small tier makes
    narrowing unsound (slicing its row drops real tail bytes — e.g.
    the constant subject of endsWith), so str_tiers must not offer a
    tier below it, and verdicts must match the full-width path."""
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.runtime.fused import STR_TIER_MIN, build_fused_plan

    long_const = "A" * (STR_TIER_MIN + 5) + "end"
    store = workloads.make_store(8)
    store.set(("rule", "istio-system", "longconst-rule"), {
        "match": f'"{long_const}".endsWith(request.path)',
        "actions": [{"handler": "denyall.istio-system",
                     "instances": ["nothing.istio-system"]}]})
    snap = SnapshotBuilder(
        default_manifest=workloads.MESH_MANIFEST).build(store)
    plan = build_fused_plan(snap)
    assert min(plan.str_tiers) >= len(long_const)
    # the verdict the clipped-constant bug flipped: subject "end"
    # (fits any tier) must stay a suffix match of the long constant
    d = workloads.make_request_dicts(4, seed=2)
    d[1]["request.path"] = "end"
    d[3]["request.path"] = "nope"
    batch = plan.engine.tensorizer.tensorize(
        [bag_from_mapping(x) for x in d])
    assert plan.narrow_batch(batch).str_bytes.shape[2] \
        >= len(long_const)
    ns = np.zeros(4, np.int32)
    got = plan.packed_check(batch, ns, observe=False)
    plan.str_tiers = (plan.engine.ruleset.layout.max_str_len,)
    full = plan.packed_check(batch, ns, observe=False)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(full))


def test_str_tier_long_strings_keep_full_width():
    plan = _tier_plan()
    if len(plan.str_tiers) < 2:
        pytest.skip("layout has no multi-tier byte planes")
    lay = plan.engine.ruleset.layout
    d = workloads.make_request_dicts(4, seed=1)
    d[2]["request.path"] = "/" + "x" * (lay.max_str_len + 10)
    batch = plan.engine.tensorizer.tensorize(
        [bag_from_mapping(x) for x in d])
    assert plan.narrow_batch(batch).str_bytes.shape[2] == \
        lay.max_str_len


def test_prewarm_warms_every_tier_shape():
    plan = _tier_plan()
    if len(plan.str_tiers) < 2:
        pytest.skip("layout has no multi-tier byte planes")
    batches = plan._prewarm_batches(8)
    widths = {plan.narrow_batch(b).str_bytes.shape[2]
              for b in batches}
    assert widths == set(plan.str_tiers)


# ---------------------------------------------------------------------------
# in-step quota prewarm wiring
# ---------------------------------------------------------------------------

def test_prewarm_instep_wired_on_publish():
    """ADVICE r5: fused.prewarm_instep existed but nothing called it.
    A quota_in_step server must have the merged check+alloc program
    compiled (the _instep_packer populated) after a config publish,
    without any quota-carrying traffic."""
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

    s = MemStore()
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 40,
                               "valid_duration_s": 10.0}]}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("rule", "istio-system", "rq-rule"), {
        "match": "", "actions": [{"handler": "mq",
                                  "instances": ["rq"]}]})
    srv = RuntimeServer(s, ServerArgs(
        fused=True, max_batch=8, buckets=(8,), quota_in_step=True,
        rulestats_drain_s=0))
    try:
        assert srv.instep_quota_target() is not None
        # the publish hook path (synchronous for swaps) — drive it
        # directly so the assertion doesn't race the init-time
        # background warm
        srv.prewarm_instep()
        assert srv.controller.dispatcher.fused._instep_packer \
            is not None
    finally:
        srv.close()
