"""Headline benchmark: batched Mixer Check() throughput at 10k rules.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "checks/s", "vs_baseline": N, ...}

Workload (BASELINE.json configs 1-3 mix): 10k Bookinfo/authz-flavored
rules — EQ/NEQ conjunctions, header map lookups, mTLS bool, path
prefix/glob/regex byte predicates — compiled to the fused PolicyEngine
step (batched atom eval + conjunction/rule gathers + denier/list/quota +
referenced-attr bitmap), evaluated for a 2048-request batch per step.

Baseline: the reference's Go IL interpreter costs 164-586 ns per
predicate eval, 0-4 allocs (mixer/pkg/il/interpreter/bench.baseline:3-8;
recorded in /root/repo/BASELINE.md). A 10k-rule resolve is a sequential
per-rule loop (resolver.go:202-238), so one Check() costs
10k × ~250 ns ≈ 2.5 ms ⇒ ~400 checks/s per core. vs_baseline is
measured TPU checks/s over that figure.

On non-TPU platforms (CI smoke) the shapes shrink but the metric and
baseline formula stay identical.
"""
from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# persistent XLA compilation cache: the 10k-rule step costs 20-40s of
# compile per bucket behind the device tunnel; cached artifacts survive
# across bench processes on the same machine/topology
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

PER_PREDICATE_NS = 250.0   # bench.baseline:3-8 midpoint


def _roofline_fields(engine, batch: int, step_s: float, prefix: str,
                     plan=None) -> dict:
    """Per-section roofline accounting (compiler/roofline.py): bytes
    touched + op counts derived from the COMPILED shapes, the achieved
    GB/s / TOPS vs platform peaks, `*_fraction_of_roof`, and the
    binding resource `*_bound` (hbm|mxu|host). Fail-soft: a modeling
    error never takes a section's measured numbers down."""
    from istio_tpu.compiler import roofline

    return roofline.bench_fields(engine, batch, step_s, prefix,
                                 plan=plan)


def _colocated_estimate(fields: dict, engine, small: int,
                        small_ms: float) -> dict:
    """served_native_colocated_p50_context_est_ms: the end-to-end
    latency estimate (DEMOTED to context — served_native_check_p99_ms
    is the measured headline) a
    latency-tier check would see on a COLOCATED chip at light load —
    frame + decode/tensorize + h2d + device step + overlay fold +
    respond — so the <1 ms claim is a whole-request story, not just
    the bare device-step gate. Sources: measured native stage p50s for
    the pure-host stages (tensorize/fold/respond — the tunnel never
    inflates them), the sync-subtracted latency-tier device step, a
    PCIe-bandwidth model for h2d (the measured h2d stage carries the
    ~100ms tunnel RTT a colocated chip does not pay), and the echo
    server's per-request wire cost for framing."""
    try:
        from istio_tpu.compiler.roofline import batch_plane_bytes

        stages = fields.get("served_native_stage_decomposition") or \
            fields.get("served_stage_decomposition") or {}

        def p50(stage: str, default: float) -> float:
            s = stages.get(stage)
            return float(s["p50_ms"]) if s and "p50_ms" in s \
                else default

        # tensorize p50 is per BATCH at the serving buckets — an
        # overstatement for a latency-tier batch, kept as the
        # conservative side of the estimate
        tz_ms = p50("tensorize",
                    fields.get("host_tensorize_ms_per_req", 0.01)
                    * small)
        fold_ms = p50("fold", 0.1)
        respond_ms = p50("respond", 0.1)
        h2d_bytes = batch_plane_bytes(engine.ruleset.layout, small)
        pcie_gbps = 12.0       # PCIe gen3 x16 effective
        h2d_ms = h2d_bytes / (pcie_gbps * 1e9) * 1e3 + 0.05
        ceiling = fields.get("served_native_wire_ceiling_per_sec", 0)
        frame_ms = 1e3 / ceiling if ceiling and ceiling > 0 else 0.05
        est = (frame_ms + tz_ms + h2d_ms + small_ms + fold_ms
               + respond_ms)
        # DEMOTED from headline (ISSUE 13): the measured wire
        # histogram (`served_native_check_p99_ms`) is the latency
        # number now — this composed estimate stays as context only,
        # cross-checked by latency_measured_vs_estimate in main()
        return {
            "served_native_colocated_p50_context_est_ms": round(est, 3),
            "served_native_colocated_p50_est_breakdown": {
                "frame_ms": round(frame_ms, 3),
                "tensorize_ms": round(tz_ms, 3),
                "h2d_ms": round(h2d_ms, 3),
                "device_step_ms": round(small_ms, 3),
                "fold_ms": round(fold_ms, 3),
                "respond_ms": round(respond_ms, 3),
                "latency_tier_batch": small,
            },
            "served_native_colocated_p50_est_derivation":
                "frame (echo per-request wire cost) + tensorize/fold/"
                "respond (measured native stage p50s, host work) + "
                "h2d (batch plane bytes / 12 GB/s PCIe + 50us "
                "dispatch) + latency-tier device step (sync-"
                "subtracted median) — an ESTIMATE composed from "
                "measured components, DEMOTED to context: "
                "served_native_check_p99_ms is the measured "
                "per-request headline",
        }
    except Exception as exc:
        return {"served_native_colocated_est_error":
                f"{type(exc).__name__}: {exc}"}


def _latency_floor_fields(fields: dict, engine, small: int) -> dict:
    """The latency roofline (compiler/roofline.latency_floor): the
    irreducible frame + h2d + device-step + d2h floor for a latency-
    tier batch, judged against the MEASURED wire p99 when the native
    section produced one — plus the measured-vs-estimate cross-check
    that demotes the PR 6 composed estimate to context. Fail-soft."""
    try:
        from istio_tpu.compiler.roofline import latency_floor

        ceiling = fields.get("served_native_wire_ceiling_per_sec", 0)
        frame_ms = 1e3 / ceiling if ceiling and ceiling > 0 else 0.05
        fl = latency_floor(engine, small, plan=None, frame_ms=frame_ms)
        out = {
            "served_native_latency_floor_ms": fl["floor_ms"],
            "served_native_latency_floor_breakdown": fl["breakdown"],
            "served_native_latency_floor_derivation": fl["derivation"],
            "served_native_latency_floor_batch": small,
        }
        p99 = fields.get("served_native_check_p99_ms")
        p50 = fields.get("served_native_check_p50_ms")
        if p99 is not None and p99 > 0:
            out["served_native_check_p99_vs_floor"] = round(
                p99 / max(fl["floor_ms"], 1e-6), 1)
            out["served_native_check_p99_software_gap_ms"] = round(
                max(p99 - fl["floor_ms"], 0.0), 3)
        est = fields.get("served_native_colocated_p50_context_est_ms")
        if est is not None and p50 is not None and p50 > 0:
            out["latency_measured_vs_estimate"] = {
                "measured_wire_p50_ms": p50,
                "measured_wire_p99_ms": p99,
                "estimate_p50_ms": est,
                "measured_p50_over_estimate": round(
                    p50 / max(est, 1e-6), 2),
                "headline": "served_native_check_p99_ms (measured, "
                            "C++ wire histogram)",
                "note": "estimate retained as context only; a large "
                        "ratio means queueing/batching policy, not "
                        "component drift — the floor breakdown "
                        "attributes it",
            }
        return out
    except Exception as exc:
        return {"served_native_latency_floor_error":
                f"{type(exc).__name__}: {exc}"}


def _roundtrip_s() -> float:
    """Median host↔device sync latency (tunnel RTT on axon)."""
    f = jax.jit(lambda x: x + 1)
    x = jax.numpy.ones(())
    float(f(x))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(x))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _resilience_delta(mon, base: dict) -> dict:
    """Shed/expired/fallback counter deltas vs a
    monitor.resilience_counters() baseline — the per-served-scenario
    overload record (a throughput number means something different
    when part of the offered load was shed or answered off the oracle
    path). Single home for both served benches."""
    r = mon.resilience_counters()
    out = {k: r[k] - base.get(k, 0)
           for k in ("shed_total", "expired_total", "fallback_total",
                     "batch_failures_total", "cancelled_shed_total")}
    out["breaker_state"] = r["breaker_state"]
    return out


def _med3(ts) -> tuple:
    """Sorted window times → (median, min, max), clamped positive.
    Headline numbers are judged on the median (VERDICT r4 item 5);
    min/max ride along so the artifact carries its own spread."""
    ts = sorted(max(float(t), 1e-6) for t in ts)
    return ts[len(ts) // 2], ts[0], ts[-1]


def _telemetry_overhead_fields(srv, prefix: str, n_reqs: int = 256,
                               steps: int = 4) -> dict:
    """Rule-telemetry cost ledger for a SERVED scenario: checks/sec
    through the in-process serving path with the on-device per-rule
    accumulators ON vs OFF, plus one drain's wall time (the device→
    host delta pull). Fail-soft by contract (ISSUE 4): a scenario
    without a fused plan/telemetry — or any measurement error — emits
    a note, never takes the scenario's headline numbers down."""
    try:
        from istio_tpu.testing import workloads

        plan = srv.controller.dispatcher.fused
        tele = getattr(plan, "telemetry", None) if plan is not None \
            else None
        if tele is None:
            return {prefix + "telemetry_note":
                    "no fused plan / telemetry disabled"}
        bags = workloads.make_bags(n_reqs)

        def cps() -> float:
            srv.check_many(bags)            # warm (jit, memo paths)
            t0 = time.perf_counter()
            for _ in range(steps):
                srv.check_many(bags)
            return steps * len(bags) / (time.perf_counter() - t0)

        on = cps()
        plan.telemetry = None
        try:
            off = cps()
        finally:
            plan.telemetry = tele
        t0 = time.perf_counter()
        srv.rulestats.drain()
        drain_ms = (time.perf_counter() - t0) * 1e3
        overhead = (off - on) / off * 100.0 if off > 0 else 0.0
        return {
            prefix + "telemetry_overhead_pct": round(overhead, 2),
            prefix + "telemetry_on_checks_per_sec": round(on, 1),
            prefix + "telemetry_off_checks_per_sec": round(off, 1),
            prefix + "telemetry_drain_ms": round(drain_ms, 3),
        }
    except Exception as exc:
        return {prefix + "telemetry_error":
                f"{type(exc).__name__}: {exc}"}


def _tail_fields(prefix: str, stages: dict | None,
                 forens_base: dict | None) -> dict:
    """Tail-forensics ledger for a SERVED scenario (ISSUE 14;
    fail-soft like the telemetry ledger): per-stage p99-vs-p50 skew —
    the stage whose tail diverges most from its median is where the
    scenario's p99 lives — plus the flight-recorder exemplar count,
    the control-plane events that fired in the window, and any typed
    ring drops, all deltaed against the scenario's own
    monitor.forensics_counters() baseline."""
    try:
        from istio_tpu.runtime import monitor

        out: dict = {}
        if stages:
            skew = {s: round(max(d.get("p99_ms", 0.0)
                                 - d.get("p50_ms", 0.0), 0.0), 3)
                    for s, d in stages.items()}
            out[prefix + "tail_stage_skew_ms"] = skew
            if skew:
                out[prefix + "tail_worst_stage"] = \
                    max(skew, key=skew.get)
        fc = monitor.forensics_counters()
        base = forens_base or {}
        out[prefix + "tail_slow_exemplars"] = \
            fc["slow_captured"] - base.get("slow_captured", 0)
        out[prefix + "tail_events_in_window"] = \
            fc["events_recorded"] - base.get("events_recorded", 0)
        bd = base.get("dropped", {})
        out[prefix + "tail_forensics_dropped"] = {
            r: v - bd.get(r, 0) for r, v in fc["dropped"].items()}
        return out
    except Exception as exc:
        return {prefix + "tail_error":
                f"{type(exc).__name__}: {exc}"}


def _forensics_overhead_fields(srv, prefix: str, n_reqs: int = 128,
                               steps: int = 4) -> dict:
    """Flight-recorder cost ledger (ISSUE 14 acceptance: ≤2% under
    clean traffic): checks/sec through the in-process serving path
    with the recorder ON vs OFF — the fast path is one threshold
    compare per batch, and this pins that claim per scenario.
    Fail-soft by contract."""
    try:
        from istio_tpu.runtime import forensics
        from istio_tpu.testing import workloads

        rec = forensics.RECORDER
        if not rec.enabled:
            return {prefix + "forensics_note":
                    "flight recorder disabled"}
        bags = workloads.make_bags(n_reqs)

        def cps() -> float:
            srv.check_many(bags)            # warm (jit, memo paths)
            t0 = time.perf_counter()
            for _ in range(steps):
                srv.check_many(bags)
            return steps * len(bags) / (time.perf_counter() - t0)

        on = cps()
        rec.configure(enabled=False)
        try:
            off = cps()
        finally:
            rec.configure(enabled=True)
        overhead = (off - on) / off * 100.0 if off > 0 else 0.0
        return {
            prefix + "forensics_overhead_pct": round(overhead, 2),
            prefix + "forensics_on_checks_per_sec": round(on, 1),
            prefix + "forensics_off_checks_per_sec": round(off, 1),
        }
    except Exception as exc:
        return {prefix + "forensics_error":
                f"{type(exc).__name__}: {exc}"}


def _audit_fields(srv, prefix: str, n_reqs: int = 128) -> dict:
    """Mesh-audit-plane ledger per served scenario (ISSUE 16; fail-
    soft by contract): the auditor's serving-path cost with the
    background thread ON vs OFF, the violation count over the
    scenario (must be 0 under clean load), and the fault-
    explainability rate probed with one real chaos device fault —
    injected AFTER the measurement windows so the headline numbers
    never see it.

    Overhead follows the PR 13 calibration doctrine (the forensics
    smoke's template): windows sized to ≥250ms, 7 PAIRED on/off
    windows with the within-pair order ALTERNATED (a fixed order
    turns warming drift into systematic bias), gate read off the
    lower-quartile (2nd-smallest) off/on ratio — a robust lower
    bound on real cost that one or two noisy pairs cannot fail."""
    try:
        from istio_tpu.runtime import monitor
        from istio_tpu.runtime.audit import INJECTIONS
        from istio_tpu.runtime.resilience import CHAOS
        from istio_tpu.testing import workloads

        aud = getattr(srv, "audit", None)
        if aud is None:
            return {prefix + "audit_note": "audit plane disabled"}
        base = monitor.audit_counters()
        bags = workloads.make_bags(n_reqs)

        srv.check_many(bags)   # warm (jit, memo paths)
        t0 = time.perf_counter()
        srv.check_many(bags)
        per_call = max(time.perf_counter() - t0, 1e-4)
        steps = max(4, int(0.25 / per_call))

        def window() -> float:
            t0 = time.perf_counter()
            for _s in range(steps):
                srv.check_many(bags)
            return steps * len(bags) / (time.perf_counter() - t0)

        ratios = []
        try:
            for i in range(7):
                first_on = i % 2 == 0
                if first_on:
                    aud.start()
                else:
                    aud.stop()
                a = window()
                if first_on:
                    aud.stop()
                else:
                    aud.start()
                b = window()
                on, off = (a, b) if first_on else (b, a)
                ratios.append(off / on if on > 0 else 1.0)
        finally:
            aud.start()
        low = sorted(ratios)[1]
        overhead = (low - 1.0) / low * 100.0 if low > 0 else 0.0

        # explainability probe: one injected device fault must come
        # back matched (counter:fallback_total / breaker evidence);
        # ledger reset scopes the rate to THIS scenario's injection
        INJECTIONS.reset()
        try:
            CHAOS.device_failures = 1
            srv.check_many(bags[:8])
        finally:
            CHAOS.reset()
        time.sleep(0.1)
        explain = aud.evaluate()["explainability"]

        cnt = monitor.audit_counters()
        violations = sum(cnt["violations"][inv]
                         - base["violations"][inv]
                         for inv in cnt["violations"])
        return {
            prefix + "audit_overhead_pct": round(overhead, 2),
            prefix + "audit_overhead_ok": overhead <= 2.0,
            prefix + "audit_violations": violations,
            prefix + "audit_explainability_rate": explain["rate"],
            prefix + "audit_evaluations":
                cnt["evaluations"] - base["evaluations"],
        }
    except Exception as exc:
        return {prefix + "audit_error":
                f"{type(exc).__name__}: {exc}"}


def main() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    n_rules = 10_000 if on_tpu else 1_000
    batch = 2_048 if on_tpu else 256
    steps = 30 if on_tpu else 5

    from istio_tpu.testing import workloads

    t0 = time.perf_counter()
    engine = workloads.make_engine(n_rules=n_rules, with_quota=True, jit=False)
    compile_s = time.perf_counter() - t0

    bags = workloads.make_bags(batch)
    t0 = time.perf_counter()
    ab = engine.tensorizer.tensorize(bags)
    tensorize_s = time.perf_counter() - t0
    req_ns = workloads.make_request_ns(engine, batch)

    step = jax.jit(engine.raw_step, donate_argnums=(3,))
    counts = engine.quota_counts
    params = jax.device_put(engine.params)
    ab = jax.device_put(ab)
    req_ns = jax.device_put(np.asarray(req_ns))
    t0 = time.perf_counter()
    verdict, counts = step(params, ab, req_ns, counts)
    jax.block_until_ready(verdict.status)
    trace_s = time.perf_counter() - t0

    def timed(n: int, bsz_batch, bsz_ns, c):
        """THREE n-step chained windows, one sync each: excludes
        per-call host↔device round-trip latency (the axon tunnel adds
        ~110ms per sync; a colocated server syncs via queues, not
        per-step RPC). Returns per-step wall times sorted ascending —
        headline fields are judged on the MEDIAN (VERDICT r4 item 5:
        best-of-N under ±40% tunnel variance overstates), with the
        spread reported alongside. The quota buffer is donated through
        the chain — returns the live one."""
        v, c = step(params, bsz_batch, bsz_ns, c)   # warm shape
        jax.block_until_ready(v.status)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                v, c = step(params, bsz_batch, bsz_ns, c)
            jax.block_until_ready(v.status)
            ts.append((time.perf_counter() - t0) / n)
        return sorted(ts), c

    sync_overhead = _roundtrip_s()
    ts_step, counts = timed(steps, ab, req_ns, counts)
    ts_step = [max(t - sync_overhead / steps, 1e-6) for t in ts_step]
    t_step = ts_step[1]                    # median of 3
    step_ms = float(t_step * 1e3)
    checks_per_sec = batch / t_step

    # latency-shaped config: the LATENCY TIER serves bucket-64 batches
    # (under light load — where tail latency matters — the batcher's
    # window collects few requests; heavy load rides the fat buckets
    # for throughput). Profiled r4: the step's cost has a fixed
    # rule-axis component (~0.4ms at 10k rules: per-rule index
    # structures and gathers read regardless of B) plus ~0.33ms per
    # 256 rows — B=64 lands under the 1ms budget, B=256 does not.
    # The deep window + clamp keep a fast step's number from going
    # negative under tunnel sync noise.
    small = 64 if on_tpu else 32
    ab_small = jax.device_put(engine.tensorizer.tensorize(bags[:small]))
    ns_small = jax.device_put(np.asarray(req_ns)[:small])
    # small-batch and dispatch-floor windows INTERLEAVE so both sample
    # the same tunnel-congestion regime (observed: a congested small
    # window next to a calm floor window flips the budget gate on
    # noise, with the B=64 wall exceeding the B=256 wall — physically
    # impossible for real device cost)
    triv = jax.jit(lambda x: x + 1)
    xt = jax.device_put(np.zeros((small, 64), np.float32))
    xt = triv(xt)
    jax.block_until_ready(xt)
    n_steps = steps * 2
    small_ts: list = []
    floor_ts: list = []
    v, counts = step(params, ab_small, ns_small, counts)  # warm shape
    jax.block_until_ready(v.status)
    # FIVE interleaved windows: the tier's device cost is now ~0.2ms
    # (min window) and the spread is pure tunnel jitter, so extra
    # windows are cheap and the median is what keeps the verdict
    # honest across reruns (VERDICT r4 item 2)
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            v, counts = step(params, ab_small, ns_small, counts)
        jax.block_until_ready(v.status)
        small_ts.append((time.perf_counter() - t0 - sync_overhead)
                        / n_steps)
        t0 = time.perf_counter()
        y = xt
        for _ in range(n_steps):
            y = triv(y)
        jax.block_until_ready(y)
        floor_ts.append((time.perf_counter() - t0 - sync_overhead)
                        / n_steps)
    small_ts = sorted(max(float(t * 1e3), 1e-3) for t in small_ts)
    floor_ts = sorted(max(float(t * 1e3), 0.0) for t in floor_ts)
    small_ms = small_ts[len(small_ts) // 2]   # median window
    floor_ms = floor_ts[len(floor_ts) // 2]
    # mid tier: the breakdown that keeps the budget claim honest
    # (VERDICT r3 item 2) — mid-batch cost shows the rule-axis fixed
    # component
    mid = 256 if on_tpu else 64
    ab_mid = jax.device_put(engine.tensorizer.tensorize(bags[:mid]))
    ns_mid = jax.device_put(np.asarray(req_ns)[:mid])
    ts_mid, counts = timed(steps * 4, ab_mid, ns_mid, counts)
    mid_ms = max(
        float((ts_mid[1] - sync_overhead / (steps * 4)) * 1e3), 1e-3)
    # tri-state budget gate (VERDICT r4 items 2+weak-1): judged on the
    # MEDIAN window. Congestion markers (a pure-transport floor
    # walling above the step, or B=64 walling above B=256 — both
    # physically impossible for real device cost) make the verdict
    # "unmeasurable", never a pass: congestion can only INFLATE the
    # measured wall, so a sub-budget median stays a genuine ok.
    congested = floor_ms >= small_ms or small_ms > mid_ms
    if small_ms < 1.0:
        p99_gate = "ok"
    elif congested:
        p99_gate = "unmeasurable"
    else:
        p99_gate = "fail"

    served = _served_bench(n_rules, on_tpu)
    served_native = _served_native_bench(n_rules, on_tpu)
    route = _route_bench(on_tpu)
    rbac = _rbac_bench(on_tpu)
    quota = _quota_bench(on_tpu)
    full_mesh = _full_mesh_bench(on_tpu)
    overlay = _overlay_bench(on_tpu)
    capacity = _capacity_bench(on_tpu)
    republish = _capacity_republish_bench(on_tpu)
    mesh_scaling = _mesh_scaling_bench(on_tpu)
    fleet = _fleet_bench(on_tpu)
    discovery = _discovery_bench(on_tpu)
    analysis = _analysis_bench(on_tpu)
    canary = _canary_bench(on_tpu)
    secure = _secure_bench(on_tpu)
    soak = _soak_bench(on_tpu)

    baseline_cps = 1e9 / (PER_PREDICATE_NS * n_rules)
    out = {
        "metric": f"mixer_check_throughput_{n_rules}_rules",
        "value": round(float(checks_per_sec), 1),
        "unit": "checks/s",
        "vs_baseline": round(float(checks_per_sec / baseline_cps), 2),
        "platform": platform,
        "batch": batch,
        "n_rules": n_rules,
        "step_ms": round(step_ms, 3),
        "step_ms_min": round(float(ts_step[0] * 1e3), 3),
        "step_ms_max": round(float(ts_step[-1] * 1e3), 3),
        "value_best": round(float(batch / ts_step[0]), 1),
        # VERDICT r4 item 5: the device-step headline is AMORTIZED —
        # chained multi-step windows, one sync each, MEDIAN of three
        # windows (min/max alongside), the measured sync subtracted.
        # The served_* numbers are the unamortized RPC-boundary truth.
        "step_ms_method":
            "chained-window amortized, sync-subtracted, median-of-3",
        "small_batch": small,
        "small_batch_step_ms": round(small_ms, 3),
        "small_batch_step_ms_min": round(small_ts[0], 3),
        "small_batch_step_ms_max": round(small_ts[-1], 3),
        # tri-state gate (see `congested` above): "ok" iff the MEDIAN
        # small-batch window lands under 1ms; congestion markers make
        # a non-ok verdict "unmeasurable" instead of silently passing
        # (the r4 gate auto-passed on floor>=wall, so noise could
        # only ever flip it TOWARD pass — judged weak #1)
        "p99_budget_gate": p99_gate,
        "p99_budget_ms_ok": bool(p99_gate == "ok"),
        "small_batch_breakdown": {
            "latency_tier_batch": small,
            "latency_tier_ms": round(small_ms, 3),
            "latency_tier_windows_ms": [round(t, 3) for t in small_ts],
            "mid_batch": mid,
            "mid_batch_ms": round(mid_ms, 3),
            "dispatch_floor_ms": round(floor_ms, 3),
            "transport_dominated": bool(floor_ms >= 0.5 * small_ms),
            "small_window_congested": bool(congested),
            "note": "fixed rule-axis cost + ~linear per-row cost; "
                    "the latency tier serves bucket-64 batches; "
                    "dispatch_floor is tunnel transport a colocated "
                    "chip does not pay; wall and floor are pipelined "
                    "chains (overlapping), so their difference is NOT "
                    "a device-time estimate",
        },
        "ruleset_compile_s": round(compile_s, 2),
        "first_step_s": round(trace_s, 2),
        "host_tensorize_ms_per_req": round(tensorize_s / batch * 1e3, 4),
        "baseline_checks_per_sec": round(baseline_cps, 1),
        "baseline_source": "mixer/pkg/il/interpreter/bench.baseline:3-8 "
                           f"({PER_PREDICATE_NS:.0f} ns/predicate x "
                           f"{n_rules} rules)",
        # roofline accounting for the headline step (raw engine step,
        # no packer): bytes/ops from the compiled shapes vs v5e peaks
        **_roofline_fields(engine, batch, t_step, "headline_"),
    }
    out.update(served)
    if "served_checks_per_sec" in served:
        out["served_vs_baseline"] = round(
            served["served_checks_per_sec"] / baseline_cps, 2)
        # honesty note (VERDICT r4 weak #7): unary served through the
        # PYTHON grpc front is bounded by that stack's loopback
        # ceiling (served_grpc_ceiling_per_sec), not by the engine —
        # the native front below is the unary number to judge
        if "served_grpc_ceiling_per_sec" in served:
            out["served_grpc_ceiling_vs_baseline"] = round(
                served["served_grpc_ceiling_per_sec"] / baseline_cps,
                2)
    if "served_batched_checks_per_sec" in served:
        out["served_batched_vs_baseline"] = round(
            served["served_batched_checks_per_sec"] / baseline_cps, 2)
    out.update(served_native)
    if "served_native_checks_per_sec" in served_native:
        out["served_native_vs_baseline"] = round(
            served_native["served_native_checks_per_sec"]
            / baseline_cps, 2)
    # the composed end-to-end colocated-latency estimate rides next to
    # the device-step gate it contextualizes (ISSUE 6 acceptance) —
    # DEMOTED to context since ISSUE 13: the measured wire histogram
    # below is the latency headline
    out.update(_colocated_estimate(out, engine, small, small_ms))
    # measured-vs-estimate cross-check + the latency roofline floor
    # (frame + h2d + device step + d2h — the irreducible part of the
    # measured p99; everything above it is attackable software)
    out.update(_latency_floor_fields(out, engine, small))
    out.update(route)
    out.update(rbac)
    out.update(quota)
    out.update(full_mesh)
    out.update(overlay)
    out.update(capacity)
    out.update(republish)
    out.update(mesh_scaling)
    out.update(fleet)
    out.update(discovery)
    out.update(analysis)
    out.update(canary)
    out.update(secure)
    out.update(soak)
    print(json.dumps(out))


def _route_bench(on_tpu: bool) -> dict:
    """The shared-automaton north star's second face: VirtualService
    route matching (pilot/pkg/proxy/envoy/route.go's per-request host
    loop) compiled through the SAME ruleset engine — one device step
    selects winning routes for a whole batch."""
    try:
        from istio_tpu.pilot.route_nfa import RouteTable
        from istio_tpu.testing import workloads

        n_routes = 10_000 if on_tpu else 200   # BASELINE config 3 scale
        batch = 2048 if on_tpu else 256
        services, rules = workloads.make_route_world(n_routes)
        rt = RouteTable(services, rules)
        reqs = workloads.make_route_requests(batch,
                                             n_services=len(services))
        bags = [workloads.bag_from_mapping(r) for r in reqs]
        sync_s = _roundtrip_s()

        # device step alone (sync-subtracted, like step_ms above; the
        # deep window + clamp keep a fast step's number from going
        # negative under tunnel sync noise)
        ab = jax.device_put(rt.tensorizer.tensorize(bags))
        params = jax.device_put(rt.program.params)
        fn = rt.program.fn
        m, _, _ = fn(params, ab)
        jax.block_until_ready(m)
        dev_best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(30):
                m, _, _ = fn(params, ab)
            jax.block_until_ready(m)
            dev_best = min(dev_best,
                           (time.perf_counter() - t0 - sync_s) / 30)
        dev_best = max(dev_best, 1e-6)

        # FULL selection through the wire fast path (select_wire: C++
        # decode + one device match+argmax program), PIPELINED: M
        # batches dispatched back-to-back, one sync at the end — XLA
        # queues the steps, so throughput is what the route tier
        # sustains, not 1/latency of a single batch behind a ~100ms
        # tunnel RTT (a colocated chip syncs in µs; the per-batch
        # latency floor is device_sync_ms in the served section)
        from istio_tpu.api import mixer_pb2 as pb
        from istio_tpu.api.wire import bag_to_compressed

        wires = []
        for r in reqs:
            msg = pb.CompressedAttributes()
            bag_to_compressed(r, msg=msg)
            wires.append(msg.SerializeToString())
        sel = np.asarray(rt.select_wire(wires))   # warm + parity batch
        # parity sampled from the BENCH batch itself (VERDICT r3 weak
        # #7): perf and correctness must not drift apart
        n_par = min(64, len(reqs))
        host_sel = np.asarray([rt.select_host(r)
                               for r in reqs[:n_par]], np.int64)
        parity_ok = bool((sel[:n_par] == host_sel).all())
        # throughput at B=8192 (4 × the request set): per-launch
        # dispatch cost behind the tunnel (~15-20ms) amortizes over
        # more rows; beyond ~8k the H2D transfer grows linearly and
        # wins again
        mult = 4 if on_tpu else 1
        big = wires * mult
        rt.select_wire(big)   # warm the big shape
        m_pipe = 4 if on_tpu else 2
        full_ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            outs = [rt.select_wire(big, block=False)
                    for _ in range(m_pipe)]
            jax.block_until_ready(outs)
            full_ts.append((time.perf_counter() - t0 - sync_s) / m_pipe)
        full_med, full_min, full_max = _med3(full_ts)
        t0 = time.perf_counter()
        rt.tensorizer.tensorize(bags)
        tensorize_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        if rt.native is not None:
            rt.native.tensorize_wire(wires)
        wire_tensorize_s = time.perf_counter() - t0
        out = {"route_rules": n_routes,
               "route_host_fallback_rules":
                   len(rt.program.host_fallback),
               "route_native": rt.native is not None,
               "route_parity_ok": parity_ok,
               "route_parity_n": n_par,
               "route_match_per_sec": round(len(big) / full_med, 1),
               "route_match_per_sec_min": round(len(big) / full_max, 1),
               "route_match_per_sec_max": round(len(big) / full_min, 1),
               "route_windows": 3,
               "route_select_batch": len(big),
               "route_select_ms": round(full_med * 1e3, 3),
               "route_pipeline": m_pipe,
               "route_tensorize_ms": round(tensorize_s * 1e3, 3),
               "route_device_step_ms": round(dev_best * 1e3, 3)}
        if rt.native is not None:
            # transport decomposition: with a colocated chip (µs sync,
            # GB/s PCIe) the select is bounded by C++ tensorize +
            # device step — report that floor so the tunnel-bound
            # measured number carries its context. Only meaningful on
            # the native path (without the shim, select_wire served
            # the python fallback and these fields would mislabel it)
            out["route_wire_tensorize_ms"] = round(
                wire_tensorize_s * 1e3, 3)
            out["route_colocated_floor_per_sec"] = round(
                batch / (wire_tensorize_s + dev_best), 1)
        return out
    except Exception as exc:
        return {"route_error": f"{type(exc).__name__}: {exc}"}


def _rbac_bench(on_tpu: bool) -> dict:
    """BASELINE config 2: 1k RBAC role rules compiled to device
    pseudo-rules (compiler/rbac_lower.py) and evaluated as extra rows
    of the one batched match program.

    Baseline: the reference's HandleAuthorization
    (mixer/adapter/rbac/rbac.go:181) is a per-request host loop over
    every (binding, subject, role-rule) triple with stringMatch fields.
    At the bench.baseline predicate cost scale (~250 ns per evaluated
    comparison) and ~1 comparison per triple before the typical
    early-continue, 1k triples ≈ 250 µs/check ≈ 4k checks/s/core — the
    derived CPU reference point this section reports against."""
    try:
        from istio_tpu.runtime.config import SnapshotBuilder
        from istio_tpu.runtime.fused import build_fused_plan
        from istio_tpu.testing import workloads

        n_roles = 1000 if on_tpu else 100
        batch = 2048 if on_tpu else 256
        steps = 40 if on_tpu else 5   # window ≫ tunnel sync jitter
        store = workloads.make_rbac_store(n_roles)
        t0 = time.perf_counter()
        snap = SnapshotBuilder(
            default_manifest=workloads.MESH_MANIFEST).build(store)
        plan = build_fused_plan(snap)
        compile_s = time.perf_counter() - t0
        groups = list(snap.rbac_groups.values())
        if not groups or not groups[0].lowered:
            return {"rbac_error": "policy did not lower: " +
                    (groups[0].reason if groups else "no group")}
        g = groups[0]
        engine = plan.engine
        dicts = workloads.make_rbac_request_dicts(batch)
        bags = [workloads.bag_from_mapping(d) for d in dicts]
        t0 = time.perf_counter()
        ab = engine.tensorizer.tensorize(bags)
        tensorize_s = time.perf_counter() - t0
        ns_ids = np.full(batch, snap.ruleset.namespace_id("default"),
                         np.int32)
        params = jax.device_put(engine.params)
        ab = jax.device_put(ab)
        ns_ids = jax.device_put(ns_ids)
        step = jax.jit(engine.raw_step)
        counts = engine.quota_counts
        v, _ = step(params, ab, ns_ids, counts)
        jax.block_until_ready(v.status)
        sync_s = _roundtrip_s()
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                v, _ = step(params, ab, ns_ids, counts)
            jax.block_until_ready(v.status)
            ts.append((time.perf_counter() - t0 - sync_s) / steps)
        med, t_min, t_max = _med3(ts)
        denied = float(np.asarray(v.status != 0).mean())
        baseline = 1e9 / (PER_PREDICATE_NS * g.n_triples)
        cps = batch / med
        return {"rbac_role_rules": n_roles,
                "rbac_pseudo_rules": len(g.allow_rows),
                "rbac_triples": g.n_triples,
                "rbac_device_step_ms": round(med * 1e3, 3),
                "rbac_checks_per_sec": round(cps, 1),
                "rbac_checks_per_sec_min": round(batch / t_max, 1),
                "rbac_checks_per_sec_max": round(batch / t_min, 1),
                "rbac_tensorize_ms_per_req":
                    round(tensorize_s / batch * 1e3, 4),
                "rbac_compile_s": round(compile_s, 2),
                "rbac_denied_frac": round(denied, 3),
                "rbac_baseline_checks_per_sec": round(baseline, 1),
                "rbac_vs_baseline": round(cps / baseline, 2),
                **_roofline_fields(engine, batch, med, "rbac_")}
    except Exception as exc:
        return {"rbac_error": f"{type(exc).__name__}: {exc}"}


def _full_mesh_bench(on_tpu: bool) -> dict:
    """BASELINE config 5 — the stated north-star demo: a generated
    5k-service topology's mTLS SAN whitelists + 1k-role RBAC authz +
    mesh-wide device quota + 5k route-NFA rows compiled into ONE
    ruleset, with check verdicts AND winning routes computed by ONE
    device program per 2048-request batch.

    Baseline: the reference evaluates each piece as a separate host
    loop — ~(5k SAN + 1k rbac triple + 5k route) predicate evals ×
    ~250 ns (bench.baseline) + a mutex'd quota op ≈ 2.8 ms/request
    ≈ ~360 checks/s/core."""
    try:
        from istio_tpu.testing import workloads

        n_services = 5000 if on_tpu else 128
        n_roles = 1000 if on_tpu else 32
        batch = 2048 if on_tpu else 128
        steps = 15 if on_tpu else 4
        t0 = time.perf_counter()
        engine, lo, hi, weights, meta = workloads.make_full_mesh(
            n_services=n_services, n_roles=n_roles)
        compile_s = time.perf_counter() - t0
        reqs = workloads.make_full_mesh_requests(
            batch, n_services, n_roles=n_roles,
            rules_by_host=meta["rules_by_host"])
        bags = [workloads.bag_from_mapping(r) for r in reqs]
        t0 = time.perf_counter()
        ab = engine.tensorizer.tensorize(bags)
        tensorize_s = time.perf_counter() - t0

        import jax.numpy as jnp
        w = jnp.asarray(weights)
        default_route = hi - lo
        raw = engine.raw_step

        def full_step(params, batch_, ns, counts):
            verdict, counts = raw(params, batch_, ns, counts)
            scores = verdict.matched[:, lo:hi] * w[None, :]
            best = jnp.argmax(scores, axis=1)
            hit = jnp.max(scores, axis=1) > 0
            route = jnp.where(hit, best, default_route)
            return verdict.status, route, counts

        step = jax.jit(full_step)
        params = jax.device_put(engine.params)
        ab = jax.device_put(ab)
        ns = jax.device_put(np.zeros(batch, np.int32))
        counts = engine.quota_counts
        status, route, counts = step(params, ab, ns, counts)
        jax.block_until_ready(status)
        sync_s = _roundtrip_s()
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                status, route, counts = step(params, ab, ns, counts)
            jax.block_until_ready(status)
            ts.append((time.perf_counter() - t0 - sync_s) / steps)
        med, t_min, t_max = _med3(ts)
        denied = float(np.asarray(status != 0).mean())
        routed = float(np.asarray(route != default_route).mean())
        # rule-telemetry overhead at full-mesh scale (ISSUE 4
        # acceptance gate: ≤ 5%): the same verdict step chained with
        # vs without the per-rule accumulator fold. Engine-level — the
        # full_mesh scenario has no served front, so the fold rides
        # the raw step exactly as packed_check would carry it.
        tele_fields: dict = {}
        try:
            from istio_tpu.runtime.rulestats import RuleTelemetry

            tele = RuleTelemetry(engine.ruleset,
                                 engine.ruleset.n_rules)
            vstep = jax.jit(raw)
            ns_np = np.zeros(batch, np.int32)
            real = np.ones(batch, bool)

            def window(observe: bool) -> float:
                c = counts
                v, c = vstep(params, ab, ns, c)     # warm
                if observe:
                    tele.observe(v, ns_np, real)
                    tele.wait()
                jax.block_until_ready(v.status)
                t0 = time.perf_counter()
                for _ in range(steps):
                    v, c = vstep(params, ab, ns, c)
                    if observe:
                        tele.observe(v, ns_np, real)
                if observe:
                    tele.wait()
                jax.block_until_ready(v.status)
                return (time.perf_counter() - t0 - sync_s) / steps

            t_off = _med3([window(False) for _ in range(3)])[0]
            t_on = _med3([window(True) for _ in range(3)])[0]
            t0 = time.perf_counter()
            tele.drain()
            drain_ms = (time.perf_counter() - t0) * 1e3
            overhead = (t_on - t_off) / t_off * 100.0
            tele_fields = {
                "full_mesh_telemetry_overhead_pct": round(overhead, 2),
                "full_mesh_telemetry_overhead_ok":
                    bool(overhead <= 5.0),
                "full_mesh_telemetry_step_on_ms": round(t_on * 1e3, 3),
                "full_mesh_telemetry_step_off_ms": round(
                    t_off * 1e3, 3),
                "full_mesh_telemetry_drain_ms": round(drain_ms, 3),
            }
        except Exception as exc:   # fail-soft like the served fields
            tele_fields = {"full_mesh_telemetry_error":
                           f"{type(exc).__name__}: {exc}"}
        n_preds = n_services + meta["n_routes"] + meta["n_triples"]
        baseline = 1e9 / (PER_PREDICATE_NS * n_preds + 1000.0)
        cps = batch / med
        return {"full_mesh_services": n_services,
                "full_mesh_rows": meta["n_rows"],
                "full_mesh_routes": meta["n_routes"],
                "full_mesh_rbac_triples": meta["n_triples"],
                "full_mesh_host_fallback": meta["host_fallback"],
                "full_mesh_step_ms": round(med * 1e3, 3),
                "full_mesh_checks_per_sec": round(cps, 1),
                "full_mesh_checks_per_sec_min": round(batch / t_max, 1),
                "full_mesh_checks_per_sec_max": round(batch / t_min, 1),
                "full_mesh_tensorize_ms_per_req":
                    round(tensorize_s / batch * 1e3, 4),
                "full_mesh_compile_s": round(compile_s, 2),
                "full_mesh_denied_frac": round(denied, 3),
                "full_mesh_routed_frac": round(routed, 3),
                # stated traffic mix (routed+authorized,
                # routed+rbac-denied, conformant SAN/authz, random)
                "full_mesh_traffic_mix": list(workloads.FULL_MESH_MIX),
                "full_mesh_baseline_checks_per_sec": round(baseline, 1),
                "full_mesh_vs_baseline": round(cps / baseline, 2),
                **_roofline_fields(engine, batch, med, "full_mesh_"),
                **tele_fields}
    except Exception as exc:
        return {"full_mesh_error": f"{type(exc).__name__}: {exc}"}


def _overlay_bench(on_tpu: bool) -> dict:
    """Host-overlay-heavy serving envelope (VERDICT r2 weak #4): 10%
    of rules carry list work the device GENUINELY cannot absorb
    (case-insensitive membership, provider-refreshed entries,
    non-DFA-compilable REGEX entries — r4's lowering ate the old
    REGEX-only workload and this bench silently measured zero host
    actions), so every matching request drops adapter work onto
    single-core python. The dispatcher-level throughput (device step
    + per-request overlay) bounds what such a config can serve; the
    cross-run spread is recorded because host-adapter work is the one
    serving leg with real run-to-run variance (ROADMAP item 4)."""
    try:
        from istio_tpu.runtime import RuntimeServer, ServerArgs
        from istio_tpu.testing import workloads

        n_rules = 10_000 if on_tpu else 500
        batch = 2048 if on_tpu else 128
        store = workloads.make_store(n_rules, host_overlay_every=10)
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.001, max_batch=batch, buckets=(batch,),
            default_manifest=workloads.MESH_MANIFEST))
        try:
            plan = srv.controller.dispatcher.fused
            n_overlay = len(plan.host_actions)
            bags = workloads.make_bags(batch, seed=9)
            srv.check_many(bags)   # warm
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                srv.check_many(bags)
                ts.append(time.perf_counter() - t0)
            fused_lists = plan.fused_lists
            unfused = list(plan.unfused_list_kinds)
        finally:
            srv.close()
        med, t_min, t_max = _med3(ts)
        cps = batch / med
        baseline = 1e9 / (PER_PREDICATE_NS * n_rules)
        out = {"overlay_rules": n_overlay,
               # a zero here means the workload regressed back into
               # the lowerable envelope and the section measures
               # nothing (the r4 failure mode) — flagged, not silent
               "overlay_measures_host_actions": bool(n_overlay > 0),
               "overlay_fused_lists": fused_lists,
               "overlay_unfused_kinds": unfused,
               "overlay_checks_per_sec": round(cps, 1),
               "overlay_checks_per_sec_min": round(batch / t_max, 1),
               "overlay_checks_per_sec_max": round(batch / t_min, 1),
               # cross-run spread (max/min wall over the 3 timed
               # runs): ROADMAP item 4's ≤1.5x done-bar is judged on
               # this number
               "overlay_cross_run_spread": round(t_max / t_min, 2)
               if t_min > 0 else -1.0,
               "overlay_batch_ms": round(med * 1e3, 1),
               "overlay_vs_baseline": round(cps / baseline, 2)}
        out.update(_overlay_executor_bench(store, n_rules, batch))
        out.update(_overlay_native_executor_bench(store, n_rules,
                                                 batch, on_tpu))
        out.update(_overlay_opa_bench(on_tpu))
        return out
    except Exception as exc:
        return {"overlay_error": f"{type(exc).__name__}: {exc}"}


def _overlay_native_executor_bench(store, n_rules: int, batch: int,
                                   on_tpu: bool) -> dict:
    """The PR 11 executor overlay scenario driven through the NATIVE
    front's bench windows (the follow-on ROADMAP item 2 left open):
    every request carries one host list action with the same injected
    2ms adapter hop as the in-process sweep, served over the real C++
    HTTP/2 wire by h2load closed-loop windows — so overlay throughput
    scaling with executor workers is proven at the wire, not just at
    the dispatcher. The wire latency histogram rides along: the
    overlay_native_p99_ms numbers are measured per-request C++
    timestamps, same clock as served_native_check_p99_ms.
    Keys: overlay_native_executor_workers,
    overlay_native_throughput_vs_workers,
    overlay_native_executor_scaling, overlay_native_spread,
    overlay_native_p99_ms_by_workers."""
    from istio_tpu.api.native_server import NativeMixerServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.testing import perf, workloads

    ADAPTER_LAT_S = _OVERLAY_EXEC_ADAPTER_LAT_S
    handlers = _OVERLAY_EXEC_HANDLERS
    dicts = _overlay_exec_dicts(n_rules, min(batch, 256))
    payloads = perf.make_check_payloads(dicts)
    workers = (1, 4)
    depth = 256 if on_tpu else 64
    n_rec = 2000 if on_tpu else 200
    try:
        vs: dict[str, float] = {}
        p99s: dict[str, float] = {}
        worst_spread = 0.0
        for w in workers:
            srv = native = None
            try:
                srv = RuntimeServer(store, ServerArgs(
                    batch_window_s=0.001, max_batch=batch,
                    buckets=(batch,), executor_workers=w,
                    default_manifest=workloads.MESH_MANIFEST))
                native = NativeMixerServer(srv, max_batch=batch,
                                           min_fill=max(batch // 4, 8),
                                           window_us=2_000, pumps=2)
                port = native.start()
                perf.run_h2load(port, payloads, 100, depth, 0.5)
                CHAOS.adapter_latency_s = {
                    h: ADAPTER_LAT_S for h in handlers}
                reps, wires = [], []
                for i in range(3):
                    base = native.latency_raw()
                    reps.append(perf.run_h2load(
                        port, payloads, n_rec, depth, 0.3))
                    wires.append(
                        native.latency_snapshot(since=base))
            finally:
                # constructor-failure-safe: a NativeMixerServer that
                # never built must not leak the RuntimeServer's
                # threads/plans into the rest of the bench run
                CHAOS.reset()
                if native is not None:
                    native.stop()
                if srv is not None:
                    srv.close()
            cps = sorted(r["checks_per_sec"] for r in reps)
            vs[str(w)] = round(cps[1], 1)
            if cps[0] > 0:
                worst_spread = max(worst_spread, cps[-1] / cps[0])
            wp = sorted(x.get("p99", 0.0) for x in wires)
            p99s[str(w)] = round(wp[1], 3)
        lo, hi = vs[str(workers[0])], vs[str(workers[-1])]
        return {
            "overlay_native_executor_workers": list(workers),
            "overlay_native_throughput_vs_workers": vs,
            "overlay_native_executor_scaling":
                round(hi / lo, 2) if lo > 0 else -1.0,
            "overlay_native_spread": round(worst_spread, 2),
            "overlay_native_p99_ms_by_workers": p99s,
            "overlay_native_adapter_latency_ms": ADAPTER_LAT_S * 1e3,
            "overlay_native_depth": depth,
        }
    except Exception as exc:
        return {"overlay_native_error":
                f"{type(exc).__name__}: {exc}"}


# the executor overlay scenario shared by the in-process and native
# sweeps: every request targets an overlay rule (one host list action
# per request) and the injected per-call adapter latency stands in
# for the external backend RPC the bulkhead lanes exist to overlap
_OVERLAY_EXEC_HANDLERS = ("cilist.istio-system", "provlist.istio-system",
                          "dynpat.istio-system")
_OVERLAY_EXEC_ADAPTER_LAT_S = 0.002


def _overlay_exec_dicts(n_rules: int, count: int) -> list[dict]:
    """Request dicts hitting make_store(host_overlay_every=10)'s
    overlay rules — the single home of the executor-sweep workload."""
    n_services = max(n_rules // 2, 1)
    overlay_rules = list(range(2, n_rules, 10))
    return [{
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        "request.path": f"/api/v{i % 3}/items",
    } for i in (overlay_rules[j % len(overlay_rules)]
                for j in range(count))]


def _overlay_executor_bench(store, n_rules: int, batch: int) -> dict:
    """Throughput vs adapter-executor workers (ISSUE 12 / ROADMAP
    item 2's done-bar): every request targets an overlay rule so each
    carries exactly one host list action, and a 2ms per-call adapter
    latency (ADAPTER_LAT_S, reported as
    overlay_executor_adapter_latency_ms) is injected at the chaos
    seam — the stand-in for the external backend RPC (a real list
    provider / OPA sidecar / quota store hop) whose wall the bulkhead
    lanes exist to overlap.
    Keys: overlay_executor_workers, overlay_throughput_vs_workers
    (checks/s per worker count), overlay_executor_scaling (highest /
    lowest worker count's throughput — >1 means host-action wall
    genuinely overlaps), overlay_executor_spread (worst cross-run
    max/min)."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import monitor as _monitor
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.testing import workloads

    # big enough that the injected host-action wall dominates the
    # ~30ms device+fold floor (128 actions / 3 lanes × 2ms ≈ 85ms at
    # one worker per lane) — a 0.5ms hop drowned in single-core noise
    ADAPTER_LAT_S = _OVERLAY_EXEC_ADAPTER_LAT_S
    handlers = _OVERLAY_EXEC_HANDLERS
    bags = [bag_from_mapping(d)
            for d in _overlay_exec_dicts(n_rules, batch)]
    workers = (1, 4)
    try:
        vs: dict[str, float] = {}
        worst_spread = 0.0
        fired = 0
        for w in workers:
            srv = RuntimeServer(store, ServerArgs(
                batch_window_s=0.001, max_batch=batch,
                buckets=(batch,), executor_workers=w,
                default_manifest=workloads.MESH_MANIFEST))
            try:
                srv.check_many(bags)   # warm (no injected latency)
                CHAOS.adapter_latency_s = {
                    h: ADAPTER_LAT_S for h in handlers}
                h0 = _monitor.host_action_counters()["submitted"]
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    srv.check_many(bags)
                    ts.append(time.perf_counter() - t0)
                fired = (_monitor.host_action_counters()["submitted"]
                         - h0) // 3
            finally:
                CHAOS.reset()
                srv.close()
            med, t_min, t_max = _med3(ts)
            vs[str(w)] = round(batch / med, 1)
            if t_min > 0:
                worst_spread = max(worst_spread, t_max / t_min)
        lo, hi = vs[str(workers[0])], vs[str(workers[-1])]
        return {
            "overlay_executor_workers": list(workers),
            "overlay_throughput_vs_workers": vs,
            "overlay_executor_scaling":
                round(hi / lo, 2) if lo > 0 else -1.0,
            "overlay_executor_spread": round(worst_spread, 2),
            "overlay_executor_actions_per_batch": int(fired),
            "overlay_executor_adapter_latency_ms":
                ADAPTER_LAT_S * 1e3,
        }
    except Exception as exc:
        return {"overlay_executor_error":
                f"{type(exc).__name__}: {exc}"}


def _overlay_opa_bench(on_tpu: bool) -> dict:
    """The rego/OPA engine as a benched overlay scenario: every
    request fires a real Rego policy evaluation on the executor's opa
    lane, with an EXACT status parity gate against the generic host
    oracle path (overlay_opa_parity_ok — the executor changes where
    adapter work runs, never what it answers)."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import workloads

    n_rules = 2000 if on_tpu else 200
    batch = 512 if on_tpu else 128
    try:
        store = workloads.make_opa_store(n_rules)
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.001, max_batch=batch, buckets=(batch,),
            default_manifest=workloads.MESH_MANIFEST))
        try:
            bags = [bag_from_mapping(x) for x in
                    workloads.make_opa_requests(batch, n_rules)]
            d = srv.controller.dispatcher
            srv.check_many(bags)   # warm
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                out = srv.check_many(bags)
                ts.append(time.perf_counter() - t0)
            fused = [r.status_code for r in out]
            oracle = [r.status_code
                      for r in d.check_host_oracle(bags)]
        finally:
            srv.close()
        med, t_min, t_max = _med3(ts)
        return {
            "overlay_opa_rules": n_rules,
            "overlay_opa_checks_per_sec": round(batch / med, 1),
            "overlay_opa_batch_ms": round(med * 1e3, 1),
            "overlay_opa_denies": sum(1 for s in fused if s == 7),
            "overlay_opa_parity_ok": fused == oracle,
            "overlay_opa_cross_run_spread":
                round(t_max / t_min, 2) if t_min > 0 else -1.0,
        }
    except Exception as exc:
        return {"overlay_opa_error": f"{type(exc).__name__}: {exc}"}


_MESH_CHILD = r"""
import json, os, time, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")   # before any backend init
import numpy as np
sys.path.insert(0, {repo!r})
from istio_tpu.runtime import RuntimeServer, ServerArgs
from istio_tpu.testing import workloads

n_rules, batch, steps = {n_rules}, {batch}, {steps}
out = {{"mesh_rules": n_rules, "mesh_batch": batch,
        "mesh_host_cores": os.cpu_count() or 1,   # None on exotic hosts
        "mesh_virtual_devices": len(jax.devices())}}
bags = workloads.make_bags(batch, seed=17)
# (label, mesh_shape, rule count): dp1/dp4mp2 pin the strong-scaling
# ratio; mp2 @ n_rules vs dp1 @ n_rules/2 is the WEAK-scaling pair
# (VERDICT r4 item 9) — each mp=2 shard holds ~n_rules/2 rule rows,
# so on a 1-core host the ideal serialized cost of the sharded step
# is 2x the half-size single-device step, and any excess is the
# sharding machinery's own overhead (collectives, psum fold, infeed).
configs = (("dp1", None, n_rules), ("dp4mp2", (4, 2), n_rules),
           ("mp2", (1, 2), n_rules), ("half", None, n_rules // 2))
times = {{}}
servers = {{}}
for label, shape, nr in configs:
    srv = RuntimeServer(workloads.make_store(nr), ServerArgs(
        batch_window_s=0.001, mesh_shape=shape, buckets=(batch,),
        # check_many warms the serving shape in-line below; the
        # background initial prewarm would contend for the one core
        initial_prewarm=False,
        default_manifest=workloads.MESH_MANIFEST))
    try:
        if label == "dp1":
            # per-shard work accounting off the served snapshot —
            # diagnostics, best-effort: never take the throughput
            # measurements down with it
            try:
                d = srv.controller.dispatcher
                rs = d.snapshot.ruleset
                n_rows = int(rs.rule_ns.shape[0])
                ab = d.snapshot.tensorizer.tensorize(bags)
                h2d = sum(int(a.nbytes) for a in (
                    ab.ids, ab.present, ab.map_present, ab.str_bytes,
                    ab.str_lens) if a is not None)
                if ab.hash_ids is not None:
                    h2d += int(ab.hash_ids.nbytes)
                out["mesh_rule_rows_total"] = n_rows
                out["mesh_mp2_rows_per_shard"] = n_rows // 2
                out["mesh_h2d_bytes_per_step"] = h2d
                out["mesh_dp4_h2d_bytes_per_shard"] = h2d // 4
            except Exception as exc:
                out["mesh_accounting_error"] = \
                    type(exc).__name__ + ": " + str(exc)
        srv.check_many(bags)          # warm/compile
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(steps):
                srv.check_many(bags)
            best = min(best, (time.perf_counter() - t0) / steps)
        if label == "dp4mp2":
            # per-stage attribution (shard dispatch / collective-free
            # match / verdict fold + its psum) — the number a reader
            # can trust even where the 1-core end-to-end ratio is
            # time-slicing noise. Diagnostics: never take the
            # throughput measurements down with it.
            try:
                from istio_tpu.parallel.mesh import mesh_stage_probe
                d = srv.controller.dispatcher
                ab = d.snapshot.tensorizer.tensorize(bags)
                ns = d._request_ns_ids(bags)
                out["mesh_dp4mp2_stage_ms"] = mesh_stage_probe(
                    srv.controller.mesh, d.fused.engine, ab, ns,
                    steps=steps)
            except Exception as exc:
                out["mesh_stage_error"] = \
                    type(exc).__name__ + ": " + str(exc)
    except BaseException:
        srv.close()
        raise
    times[label] = best
    if label in ("mp2", "half"):
        servers[label] = srv    # kept open for the interleaved pass
    else:
        srv.close()
# the weak-scaling pair re-measures INTERLEAVED (mp2/half/mp2/half)
# with both servers alive: measured minutes apart, host drift between
# the two configs swung mesh_overhead_ratio 1.05-1.5x run to run —
# alternating windows sample the same host conditions for both sides.
# The RATIO uses interleaved-pass times ONLY (mixing a quiet solo
# window into one side would re-compare unmatched conditions); the
# standalone throughput fields keep the overall best.
pair = {{"mp2": float("inf"), "half": float("inf")}}
try:
    for _ in range(3):
        for label in ("mp2", "half"):
            servers[label].check_many(bags)   # re-warm page residency
            t0 = time.perf_counter()
            for _ in range(steps):
                servers[label].check_many(bags)
            pair[label] = min(pair[label],
                              (time.perf_counter() - t0) / steps)
            times[label] = min(times[label], pair[label])
finally:
    for srv in servers.values():
        srv.close()
for label, _shape, _nr in configs:
    out[f"mesh_{{label}}_checks_per_sec"] = round(
        batch / times[label], 1)
# honesty gate (ISSUE 6 satellite): whenever the host has fewer
# cores than virtual devices the shards time-slice, so the dp
# scaling ratio is sign-flipping noise (r5 artifacts: 0.82 vs 1.07
# across runs) — it is only printed where every virtual device has
# a core of its own; the per-stage timers above attribute the
# sharding overhead either way.
out["mesh_perf_informative"] = (
    out["mesh_host_cores"] >= out["mesh_virtual_devices"])
if out["mesh_perf_informative"]:
    out["mesh_scaling_ratio"] = round(
        out["mesh_dp4mp2_checks_per_sec"]
        / out["mesh_dp1_checks_per_sec"], 3)
else:
    out["mesh_scaling_note"] = (
        f"mesh_host_cores={{out['mesh_host_cores']}} < "
        f"{{out['mesh_virtual_devices']}} virtual devices: dp "
        "scaling over time-sliced virtual devices is uninformative; "
        "see mesh_dp4mp2_stage_ms for the per-stage "
        "sharding-overhead attribution and mesh_overhead_ratio for "
        "the weak-scaling pair")
out["mesh_overhead_ratio"] = round(
    pair["mp2"] / (2.0 * pair["half"]), 3)
out["mesh_overhead_interpretation"] = (
    "mp2@" + str(n_rules) + " step time over 2x the dp1@"
    + str(n_rules // 2) + " step time: the 1-core host serializes the "
    "two half-size shards, so ~1.0 means the sharding machinery adds "
    "nothing beyond the sharded work itself; the excess above 1.0 is "
    "sharding overhead proper (collectives, fold, dispatch) — "
    "distinct from mesh_scaling_ratio, which the 1-core wall caps")
print(json.dumps(out))
"""


def _analysis_bench(on_tpu: bool) -> dict:
    """Snapshot-analyzer cost alongside the serving numbers: static
    verification (istio_tpu/analysis) runs at every admission/CLI
    gate and per config generation on /debug/analysis, so its
    wall-time and finding counts are tracked per snapshot scenario —
    an analysis-cost regression must name itself in the BENCH json
    the same way a serving regression does."""
    try:
        from istio_tpu.analysis import (analyze_route_table,
                                        analyze_rules,
                                        analyze_snapshot)
        from istio_tpu.expr.checker import AttributeDescriptorFinder
        from istio_tpu.pilot.route_nfa import RouteTable
        from istio_tpu.runtime.config import SnapshotBuilder
        from istio_tpu.testing import corpus, workloads

        out: dict = {}
        # scenario 1: the golden serving store (clean — 0 findings)
        n_rules = 400 if on_tpu else 120
        snap = SnapshotBuilder(workloads.MESH_MANIFEST).build(
            workloads.make_store(n_rules))
        t0 = time.perf_counter()
        rep = analyze_snapshot(snap)
        out["analysis_store_rules"] = n_rules
        out["analysis_store_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        out["analysis_store_findings"] = len(rep.findings)

        # scenario 2: a route table (random world: real shadows may
        # exist and are counted, not hidden)
        n_routes = 200 if on_tpu else 60
        services, rules_by_host = workloads.make_route_world(n_routes)
        rt = RouteTable(services, rules_by_host)
        t0 = time.perf_counter()
        rep = analyze_route_table(rt, pair_budget=50_000)
        out["analysis_route_rules"] = n_routes
        out["analysis_route_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        out["analysis_route_findings"] = len(rep.findings)

        # scenario 3: the seeded fault corpus — detection wall-time +
        # the detected/seeded ratio (must stay 1.0; the analyze_gate
        # CI gate fails otherwise, this just tracks the cost)
        finder = AttributeDescriptorFinder(corpus.ANALYZER_MANIFEST)
        cases = corpus.make_analyzer_faults(20260803)
        t0 = time.perf_counter()
        detected = 0
        for case in cases:
            rep = analyze_rules(case.rules, finder,
                                deny_idx=case.deny_idx,
                                allow_idx=case.allow_idx,
                                check_totality=False)
            if any(f.code == case.kind for f in rep.errors):
                detected += 1
        out["analysis_faults_ms"] = round(
            (time.perf_counter() - t0) * 1e3, 2)
        out["analysis_faults_detected"] = f"{detected}/{len(cases)}"

        # scenario 4: meshlint — the repo-wide concurrency/discipline
        # analyzer runs as a CI gate over the package itself; its
        # wall-time and finding count ride the same artifact so a
        # call-graph blow-up names itself here, not in a stuck CI job
        try:
            from istio_tpu.analysis.meshlint import run_meshlint
            t0 = time.perf_counter()
            mrep = run_meshlint(
                root=os.path.dirname(os.path.abspath(__file__)))
            out["meshlint_wall_s"] = round(
                time.perf_counter() - t0, 3)
            out["meshlint_findings"] = len(mrep.findings)
        except Exception as exc:
            out["meshlint_error"] = f"{type(exc).__name__}: {exc}"
        return out
    except Exception as exc:   # bench sections never sink the artifact
        return {"analysis_error": f"{type(exc).__name__}: {exc}"}


def _canary_bench(on_tpu: bool) -> dict:
    """Config-canary cost alongside the serving numbers: replay
    throughput (rows/s through a candidate plan), measured divergence
    rates for an identical-semantics and a deliberately divergent
    swap, the gate verdicts, the publish delay the whole evaluation
    added, and the recorder tap's throughput overhead — the canary
    must stay a swap-time cost, never a serving-path one."""
    try:
        from istio_tpu.runtime import RuntimeServer, ServerArgs
        from istio_tpu.runtime.batcher import pad_to_bucket
        from istio_tpu.attribute.bag import bag_from_mapping
        from istio_tpu.testing import workloads

        out: dict = {}
        n_rules = 256 if on_tpu else 48
        n_reqs = 512 if on_tpu else 128
        buckets = (64, 256) if on_tpu else (32, 64)
        store = workloads.make_store(n_rules, seed=11)
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.0003, max_batch=buckets[-1],
            buckets=buckets, canary="gate", rulestats_drain_s=0,
            default_manifest=workloads.MESH_MANIFEST))
        # the bench drives rebuilds explicitly — a debounce-timer
        # rebuild racing them would run the replay twice and inflate
        # the measured publish delay (the smoke does the same)
        srv.controller.debounce_s = 600.0
        try:
            dicts = workloads.make_request_dicts(n_reqs, seed=4)
            n_srv = max(n_rules // 2, 1)
            for i in range(0, n_rules, 3):     # deny rules fire too
                dicts.append({
                    "destination.service": f"svc{i % n_srv}.ns"
                    f"{i % 23}.svc.cluster.local",
                    "source.namespace": f"ns{(i * 5) % 25}",
                    "request.method": "GET",
                    "request.path": "/api/v0/products/1",
                    "connection.mtls": True})
            bags = [bag_from_mapping(d) for d in dicts]

            def serve_all() -> float:
                t0 = time.perf_counter()
                for lo in range(0, len(bags), buckets[-1]):
                    srv.check_batch_preprocessed(pad_to_bucket(
                        bags[lo:lo + buckets[-1]], buckets))
                return time.perf_counter() - t0

            serve_all()                        # warm + record
            # recorder overhead: same padded batch, tap on vs off,
            # INTERLEAVED per-batch samples so drift hits both sides
            # equally; judged on the p99 (the acceptance budget is a
            # tail budget: recorder ≤2% p99 on served traffic)
            d = srv.controller.dispatcher
            probe = pad_to_bucket(bags[:buckets[-1]], buckets)
            rec = d.recorder
            t_on: list = []
            t_off: list = []
            for _ in range(30):
                d.recorder = rec
                t0 = time.perf_counter()
                srv.check_batch_preprocessed(probe)
                t_on.append(time.perf_counter() - t0)
                d.recorder = None
                t0 = time.perf_counter()
                srv.check_batch_preprocessed(probe)
                t_off.append(time.perf_counter() - t0)
            d.recorder = rec
            p99 = lambda ts: sorted(ts)[  # noqa: E731
                min(len(ts) - 1, int(len(ts) * 0.99))]
            med = lambda ts: sorted(ts)[len(ts) // 2]  # noqa: E731
            ov_p99 = (p99(t_on) - p99(t_off)) / p99(t_off) * 100.0
            ov_med = (med(t_on) - med(t_off)) / med(t_off) * 100.0
            # differential end-to-end overheads (informational —
            # single-batch walls swing ±15% on a contended box)
            out["canary_recorder_overhead_p99_pct"] = round(
                max(ov_p99, 0.0), 2)
            out["canary_recorder_overhead_median_pct"] = round(
                max(ov_med, 0.0), 2)
            # the acceptance gate (ISSUE 5): recorder tap ≤2% of the
            # served batch p99. Judged on a DIRECT tap timing over the
            # real served batch shape divided by the measured batch
            # wall — the tap is deterministic host python, so the
            # direct measure is noise-immune where the differential
            # walls are not
            chunk = bags[:buckets[-1]]
            resps = srv.check_batch_preprocessed(probe)[:len(chunk)]
            snap = d.snapshot
            dev = (np.array([r.status_code for r in resps], np.int32),
                   np.array([r.valid_duration_s for r in resps],
                            np.float32),
                   np.array([r.valid_use_count for r in resps],
                            np.int32),
                   np.array([r.deny_rule for r in resps], np.int32))
            t0 = time.perf_counter()
            for _ in range(50):
                rec.tap(chunk, resps, snap, d.identity_attr,
                        device=dev)
            tap_wall = (time.perf_counter() - t0) / 50
            out["canary_recorder_tap_us_per_batch"] = round(
                tap_wall * 1e6, 1)
            out["canary_recorder_overhead_ok"] = bool(
                tap_wall / p99(t_on) * 100.0 <= 2.0)
            # the probe/tap loops overwrote the ring with probe-only
            # rows; restore a representative corpus (crafted deny
            # rows included) before the swap scenarios below
            serve_all()

            # identical-semantics swap: same store contents → rebuild
            t0 = time.perf_counter()
            srv.controller.rebuild()
            out["canary_publish_delay_identical_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 1)
            rep = srv.canary.reports()[-1]
            out["canary_replay_rows_per_s"] = rep.replay_rows_per_s
            out["canary_identical_divergence_rate"] = \
                rep.divergence_rate
            verdicts = {"identical": rep.verdict}

            # divergent swap: tighten a firing deny rule's match
            ridx = 3 * ((n_rules // 2) // 3)   # a deny rule (i % 3==0)
            key = ("rule", f"ns{ridx % 23}", f"rule{ridx}")
            spec = dict(store.get(key) or {})
            spec["match"] = (spec.get("match", "") +
                             ' && request.method == "DELETE"').lstrip(
                                 " &")
            store.set(key, spec)
            t0 = time.perf_counter()
            srv.controller.rebuild()
            out["canary_publish_delay_divergent_ms"] = round(
                (time.perf_counter() - t0) * 1e3, 1)
            rep = srv.canary.reports()[-1]
            verdicts["divergent"] = rep.verdict
            out["canary_divergent_divergence_rate"] = \
                rep.divergence_rate
            out["canary_gate_verdicts"] = verdicts
            out["canary_recorded_rows"] = \
                srv.canary.recorder.stats()["entries"]
        finally:
            srv.close()
        return out
    except Exception as exc:   # bench sections never sink the artifact
        return {"canary_error": f"{type(exc).__name__}: {exc}"}


def _capacity_bench(on_tpu: bool) -> dict:
    """Rule-capacity spot check: the 50k-rule step (5× the headline
    scale) must compile and run — r4 caught a TPU kernel fault here
    that 10k-rule benches never trip (an all-False scatter-max over
    the [B, R] err plane), so the artifact pins capacity every round.
    """
    try:
        from istio_tpu.testing import workloads

        n_rules = 50_000 if on_tpu else 2_000
        batch = 1_024 if on_tpu else 128
        t0 = time.perf_counter()
        engine = workloads.make_engine(n_rules=n_rules,
                                       with_quota=False, jit=False)
        compile_s = time.perf_counter() - t0
        bags = workloads.make_bags(batch)
        ab = jax.device_put(engine.tensorizer.tensorize(bags))
        ns = jax.device_put(np.asarray(
            workloads.make_request_ns(engine, batch)))
        params = jax.device_put(engine.params)
        step = jax.jit(engine.raw_step)
        counts = engine.quota_counts
        v, counts = step(params, ab, ns, counts)
        jax.block_until_ready(v.status)
        status_dev = np.asarray(v.status)
        sync_s = _roundtrip_s()
        steps = 10 if on_tpu else 3
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                v, counts = step(params, ab, ns, counts)
            jax.block_until_ready(v.status)
            ts.append((time.perf_counter() - t0 - sync_s) / steps)
        med, t_min, t_max = _med3(ts)
        out = {"capacity_rules": n_rules,
               "capacity_batch": batch,
               "capacity_step_ms": round(med * 1e3, 2),
               "capacity_checks_per_sec": round(batch / med, 1),
               "capacity_checks_per_sec_min": round(batch / t_max, 1),
               "capacity_checks_per_sec_max": round(batch / t_min, 1),
               "capacity_compile_s": round(compile_s, 2)}
        out.update(_roofline_fields(engine, batch, med, "capacity_"))
        out.update(_capacity_parity(engine, ab, ns, status_dev,
                                    on_tpu))
        return out
    except Exception as exc:
        return {"capacity_error": f"{type(exc).__name__}: {exc}"}


def _capacity_republish_bench(on_tpu: bool) -> dict:
    """Delta-publish phase of the capacity story (ISSUE 11): a
    production mesh republishes config constantly, so the artifact
    pins what a ONE-NAMESPACE delta costs on a sharded fleet snapshot
    versus a full rebuild of every bank.

      capacity_republish_full_s    republish wall with delta
                                   compilation DISABLED — every bank
                                   recompiles (the pre-delta world)
      capacity_republish_delta_s   republish wall for a one-namespace
                                   constant edit with the content-
                                   addressed bank cache on
      capacity_banks_reused        banks carried across that delta
                                   (K-1 expected: only the edited
                                   namespace's bank recompiles)

    The edit is constant-only (a literal swap inside one rule's
    match), the dominant real-world churn shape — the compiled
    programs take their index tensors as traced arguments, so the
    delta's one recompiled bank also re-uses its XLA artifact via the
    persistent compilation cache when one is configured."""
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime.store import Event
    from istio_tpu.testing import workloads

    n_rules = 100_000 if on_tpu else 4_000
    n_ns = 512 if on_tpu else 64
    shards = 8 if on_tpu else 4
    srv = None
    try:
        store = workloads.make_fleet_store(n_rules, n_ns, seed=17)
        t0 = time.perf_counter()
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.001, max_batch=16, buckets=(16,),
            shards=shards, replicas=1, rule_telemetry=False,
            initial_prewarm=False,
            default_manifest=workloads.MESH_MANIFEST))
        build_s = time.perf_counter() - t0

        def edit_one(tag: str) -> None:
            # constant-only edit of one rule in one namespace; quiet
            # apply + explicit rebuild = exactly one deterministic
            # republish per measurement (no debounce-timer race)
            key = next(k for k in store.list("rule") if k[1] == "ns1")
            spec = dict(store.get(key))
            # prefix the first string constant (the service literal) —
            # applies cleanly no matter how many edits came before
            spec["match"] = spec["match"].replace('"', f'"{tag}-', 1)
            store.apply_events([Event(key, spec)], notify=False)

        # full republish: the kill switch makes every bank rebuild
        srv.args.delta_compile = False
        edit_one("full")
        t0 = time.perf_counter()
        srv.controller.rebuild()
        full_s = time.perf_counter() - t0

        # delta republish: diff by content hash, rebuild one bank
        srv.args.delta_compile = True
        edit_one("delta")
        t0 = time.perf_counter()
        srv.controller.rebuild()
        delta_s = time.perf_counter() - t0
        st = dict(srv._rebuild_status)
        return {
            "capacity_republish_rules": n_rules,
            "capacity_republish_shards": shards,
            "capacity_republish_build_s": round(build_s, 2),
            "capacity_republish_full_s": round(full_s, 3),
            "capacity_republish_delta_s": round(delta_s, 3),
            "capacity_banks_reused": st["banks_reused"],
            "capacity_banks_recompiled": st["banks_recompiled"],
            "capacity_republish_speedup": round(
                full_s / delta_s, 2) if delta_s > 0 else None,
        }
    except Exception as exc:
        return {"capacity_republish_error":
                f"{type(exc).__name__}: {exc}"}
    finally:
        if srv is not None:
            srv.close()


def _capacity_parity(engine, ab, ns, status_dev, on_tpu: bool) -> dict:
    """VERDICT r4 item 8: a correctness bit riding the capacity batch.
    The SAME step (first 64 rows — rows are independent; quota is
    inactive here) re-runs on the in-process CPU backend and statuses
    must agree — an independent-backend check that catches silent TPU
    kernel wrongness at the 50k-rule scale where r4 found a real
    kernel fault (commit 34d6070). Measured cost on this box: ~3s CPU
    compile + 0.2s step."""
    try:
        if not on_tpu:      # already ON cpu: the bit would be vacuous
            return {"capacity_parity_ok": True,
                    "capacity_parity_mode": "same-backend (cpu run)"}
        n_par = min(64, int(status_dev.shape[0]))
        cpu = jax.devices("cpu")[0]
        row = lambda x: np.asarray(x)[:n_par]   # noqa: E731
        ab_c = jax.device_put(jax.tree.map(row, ab), cpu)
        ns_c = jax.device_put(np.asarray(ns)[:n_par], cpu)
        params_c = jax.device_put(
            jax.tree.map(np.asarray, engine.params), cpu)
        counts_c = jax.device_put(np.asarray(engine.quota_counts), cpu)
        with jax.default_device(cpu):
            v_c, _ = jax.jit(engine.raw_step)(params_c, ab_c, ns_c,
                                              counts_c)
        status_cpu = np.asarray(v_c.status)
        ok = bool((status_cpu == status_dev[:n_par]).all())
        return {"capacity_parity_ok": ok,
                "capacity_parity_n": n_par,
                "capacity_parity_mode": "tpu-vs-cpu backend",
                **({} if ok else {"capacity_parity_mismatch": int(
                    (status_cpu != status_dev[:n_par]).sum())})}
    except Exception as exc:
        return {"capacity_parity_error": f"{type(exc).__name__}: {exc}"}


def _mesh_scaling_bench(on_tpu: bool) -> dict:
    """SURVEY §5.8 scaling artifact (VERDICT r3 item 8): dispatcher-
    level check_many throughput dp=1 vs dp=4×mp=2 on the 8-virtual-CPU
    platform, over a 10k-rule snapshot whose rule rows shard
    non-trivially across mp. Runs in a SUBPROCESS: this process owns
    the TPU backend, and the virtual mesh must force the CPU platform
    before any backend init.

    Honest framing baked into the fields: this box has ONE physical
    core, so 8 virtual devices time-slice it and the ratio measures
    the sharding machinery's OVERHEAD at scale, not a speedup — on
    real multi-chip hardware the dp axis multiplies throughput over
    ICI. The artifact pins the code path end-to-end (mesh jit +
    collectives execute for real) plus the measured ratio."""
    import subprocess
    import sys

    try:
        script = _MESH_CHILD.format(
            repo=os.path.dirname(os.path.abspath(__file__)),
            n_rules=10_000 if on_tpu else 500,
            batch=512 if on_tpu else 64,
            steps=3)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env,
            capture_output=True, text=True, timeout=1800)
        # a crash AT EXIT (e.g. a stray runtime thread aborting
        # interpreter teardown) must not discard measurements the
        # child already printed — parse the json line when present
        # and carry the exit code alongside
        lines = [ln for ln in proc.stdout.strip().splitlines()
                 if ln.startswith("{")]
        if lines:
            out = json.loads(lines[-1])
            if proc.returncode != 0:
                out["mesh_child_exit_code"] = proc.returncode
                out["mesh_child_stderr_tail"] = \
                    proc.stderr.strip()[-200:]
            return out
        return {"mesh_error":
                f"child rc={proc.returncode}: "
                f"{proc.stderr.strip()[-300:]}"}
    except Exception as exc:
        return {"mesh_error": f"{type(exc).__name__}: {exc}"}


def _fleet_bench(on_tpu: bool) -> dict:
    """Large-fleet mesh scenario (ROADMAP item 3's capacity story):
    simulated-sidecar requests (identities drawn from a 50k-sidecar
    id space; `fleet_sidecars_observed` reports the distinct count
    actually multiplexed in the measured windows) over the real
    BatchCheck wire front against a ≥100k-rule snapshot served
    through the SHARDED plane (istio_tpu/sharding — namespace-sharded
    banks × replica lanes). Namespace skew is the documented Zipf mix
    (testing/workloads.FLEET_ZIPF_A); emitted per the median-window
    doctrine:

      fleet_checks_per_sec        median of 3 closed-loop BatchCheck
                                  windows (min/max spread alongside)
      fleet_shard_balance         the planner's LPT balance audit
      fleet_shard_occupancy       rows served per bank / total
      fleet_stage_attribution     shard_dispatch / bank_check / fold
                                  decomposition, this scenario only
      fleet_parity_ok             EXACT SnapshotOracle spot-parity on
                                  a traffic subsample (status + global
                                  deny attribution)

    The replica scaling ratio follows the mesh_perf_informative
    doctrine (PR 6): lanes on a host with fewer cores than concurrent
    serving threads time-slice, so the ratio is only printed where it
    can mean something — `fleet_mesh_perf_informative` gates it, a
    note replaces it otherwise. Rule telemetry is off (a 100k-row ×
    512-namespace accumulator plane is not this scenario's subject)."""
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import monitor
    from istio_tpu.testing import workloads

    n_rules = 100_000 if on_tpu else 4_000
    n_ns = 512 if on_tpu else 128
    shards = 8 if on_tpu else 4
    replicas = 2
    # sidecar identity space the traffic draws from; the artifact
    # reports the OBSERVED distinct count in the measured windows —
    # the scale claim is what was actually multiplexed, never the
    # generator's parameter
    sidecar_ids = 50_000
    chunk = 256 if on_tpu else 32         # one sidecar's flush
    chunks_per_window = 32 if on_tpu else 8
    srv = None
    client = None
    g = None
    try:
        t0 = time.perf_counter()
        store = workloads.make_fleet_store(n_rules, n_ns, seed=17)
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.001, max_batch=chunk, buckets=(chunk,),
            shards=shards, replicas=replicas,
            rule_telemetry=False, initial_prewarm=False,
            default_manifest=workloads.MESH_MANIFEST))
        build_s = time.perf_counter() - t0
        plan = srv._sharded["plan"]
        n_req = chunk * chunks_per_window * 3
        dicts = workloads.make_fleet_traffic(n_req, n_rules, n_ns,
                                             seed=17,
                                             sidecar_ids=sidecar_ids)
        n_sidecars_observed = len({d["source.user"] for d in dicts})

        # -- the real BatchCheck wire front --------------------------
        from istio_tpu.api.client import MixerClient
        from istio_tpu.api.grpc_server import MixerGrpcServer
        g = MixerGrpcServer(runtime=srv)
        port = g.start()
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        warm = dicts[:chunk]
        client.batch_check(warm)            # warm the wire + banks
        base = monitor.shard_stage_baseline()
        rates = []
        for w in range(3):
            lo = w * chunk * chunks_per_window
            window = dicts[lo:lo + chunk * chunks_per_window]
            t0 = time.perf_counter()
            answered = 0
            for c in range(0, len(window), chunk):
                answered += len(client.batch_check(
                    window[c:c + chunk]))
            wall = time.perf_counter() - t0
            rates.append(answered / wall)
        rates.sort()
        stage = monitor.shard_latency_snapshot(since=base)["stages"]

        # -- occupancy + conservation across every lane --------------
        routing = srv.batcher.routing_stats()
        occupancy = routing["occupancy"]
        misrouted = routing["misrouted"]

        # -- exact oracle spot-parity on a subsample -----------------
        from istio_tpu.attribute.bag import bag_from_mapping
        from istio_tpu.sharding import oracle_check_statuses
        sample = [bag_from_mapping(d) for d in dicts[:16]]
        got = srv.check_many(sample)
        want = oracle_check_statuses(
            srv.controller.dispatcher.snapshot,
            srv.controller.dispatcher.fused, sample)
        mismatches = sum(
            1 for g_, w_ in zip(got, want)
            if g_.status_code != w_["status"]
            or g_.deny_rule != w_["deny_rule"])

        out = {
            "fleet_rules": n_rules,
            "fleet_namespaces": n_ns,
            "fleet_shards": shards,
            "fleet_replicas": replicas,
            # observed distinct sidecar identities in the measured
            # windows (the honest multiplexing claim) + the id space
            # they were drawn from
            "fleet_sidecars_observed": n_sidecars_observed,
            "fleet_sidecar_id_space": sidecar_ids,
            "fleet_requests": n_req,
            "fleet_zipf_a": workloads.FLEET_ZIPF_A,
            "fleet_build_s": round(build_s, 2),
            "fleet_checks_per_sec": round(rates[1], 1),
            "fleet_checks_per_sec_min": round(rates[0], 1),
            "fleet_checks_per_sec_max": round(rates[-1], 1),
            "fleet_wire": "grpc BatchCheck, closed-loop, "
                          f"{chunk}-request sidecar flushes",
            "fleet_shard_balance": plan.balance(),
            "fleet_shard_occupancy": occupancy,
            "fleet_misrouted_rows": misrouted,
            "fleet_stage_attribution": stage,
            "fleet_parity_ok": bool(mismatches == 0),
            "fleet_parity_mismatches": mismatches,
            "fleet_rule_telemetry": False,
        }

        # -- replica scaling, gated by the mesh honesty doctrine -----
        # concurrent serving threads: one flusher + one step worker
        # per lane, plus the submitting client — fewer host cores than
        # that and the lanes time-slice, making the ratio noise
        host_cores = os.cpu_count() or 1
        informative = host_cores >= 2 * replicas + 1
        out["fleet_mesh_perf_informative"] = bool(informative)
        if informative:
            bags = [bag_from_mapping(d)
                    for d in dicts[:chunk * chunks_per_window]]
            lane0 = srv.batcher.routers[0]

            def lane_rate(submit_all: bool) -> float:
                t0 = time.perf_counter()
                if submit_all:
                    futs = [srv.batcher.submit(b) for b in bags]
                    n = sum(1 for f in futs if f.result() is not None)
                else:
                    n = 0
                    for c in range(0, len(bags), chunk):
                        n += len(lane0.check(bags[c:c + chunk]))
                return n / (time.perf_counter() - t0)

            single = lane_rate(False)
            multi = lane_rate(True)
            out["fleet_single_lane_checks_per_sec"] = round(single, 1)
            out["fleet_replica_scaling_ratio"] = round(
                multi / single, 3) if single > 0 else -1.0
        else:
            out["fleet_scaling_note"] = (
                f"host_cores={host_cores} < {2 * replicas + 1} "
                "concurrent serving threads: replica lanes time-slice "
                "and the scaling ratio would be noise (the "
                "mesh_perf_informative doctrine); "
                "fleet_stage_attribution carries the trustworthy "
                "per-stage accounting either way")
        return out
    except Exception as exc:
        return {"fleet_error": f"{type(exc).__name__}: {exc}"}
    finally:
        if client is not None:
            client.close()
        if g is not None:
            g.stop()
        if srv is not None:
            srv.close()


def _discovery_bench(on_tpu: bool) -> dict:
    """Pilot discovery at fleet scale (ROADMAP item 3's second
    workload): a ≥10k-sidecar fleet polling the snapshot-served
    discovery plane (pilot/discovery.py) through a one-namespace-at-a-
    time churn storm. Emitted per the median-window doctrine:

      discovery_configs_per_sec    median of 3 full-fleet warm RDS
                                   poll windows (min/max spread
                                   alongside; in-process endpoint
                                   calls — the wire sub-window pins
                                   the HTTP front separately)
      discovery_cache_hit_rate     over the churn-storm window (only
                                   churned scopes should miss)
      discovery_push_fanout_ms_*   publish → parked-watcher wake
                                   (p50/p99 over the watcher cohort)
      discovery_parity_ok          served bytes byte-exact vs the
                                   unscoped single-node generation
                                   path on a node sample

    Honesty notes: configs/sec counts IN-PROCESS endpoint serves
    (cache-hit dict lookups — the claim is cache+snapshot efficiency,
    not HTTP stack throughput; discovery_wire_configs_per_sec is the
    stdlib-threaded-front loopback number and bounds any wire claim).
    The parity sample leans on RDS (the scoped endpoint); CDS/LDS are
    mesh-scoped by construction and their reference generation is the
    O(services x rules) live scan this plane exists to avoid — one
    node covers them."""
    import threading
    import urllib.request

    from istio_tpu.pilot.discovery import DiscoveryService
    from istio_tpu.runtime import monitor
    from istio_tpu.testing import workloads

    n_services, n_ns, replicas = 2_000, 64, 5     # 10k sidecars
    n_routes = 2_500
    storm_rounds = 8
    ds = None
    try:
        t0 = time.perf_counter()
        registry, store, nodes, meta = workloads.make_discovery_world(
            n_services=n_services, n_namespaces=n_ns,
            replicas=replicas, n_routes=n_routes, source_ns=2,
            seed=17)
        ds = DiscoveryService(registry, store)
        build_s = time.perf_counter() - t0
        port = ds.start()
        stage_base = monitor.discovery_stage_baseline()

        def fleet_poll() -> int:
            served = 0
            for idx, n in enumerate(nodes):
                k = meta["ns_of"][idx // replicas]
                ds.list_routes(str(8000 + k), "istio", n)
                served += 1
            return served

        t0 = time.perf_counter()
        fleet_poll()                        # cold: generation + fill
        cold_s = time.perf_counter() - t0
        groups = ds.cache_size
        rates = []
        for _ in range(3):
            t0 = time.perf_counter()
            served = fleet_poll()
            rates.append(served / (time.perf_counter() - t0))
        rates.sort()

        # -- real-wire sub-window (stdlib threaded front, loopback) --
        idx_of = {n: i for i, n in enumerate(nodes)}
        wire_nodes = nodes[:: max(len(nodes) // 256, 1)][:256]
        t0 = time.perf_counter()
        for n in wire_nodes:
            k = meta["ns_of"][idx_of[n] // replicas]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/v1/routes/{8000 + k}"
                    f"/istio/{n}", timeout=30) as r:
                r.read()
        wire_rate = len(wire_nodes) / (time.perf_counter() - t0)

        # -- delta push fan-out: parked watchers, one churned ns -----
        churn_k = max(meta["rules_by_ns"])
        snap = ds.snapshot
        churn_shard = snap.plan.shard_of(f"ns{churn_k}")
        watch_results: list[dict] = []
        lock = threading.Lock()

        def watcher(node: str, timeout: float) -> None:
            out = ds.watch(node, ds.generation, timeout)
            with lock:
                watch_results.append(out)

        watchers = []
        in_scope = meta["nodes_by_ns"][churn_k][:64]
        out_scope = [n for k, ns_nodes in meta["nodes_by_ns"].items()
                     if snap.plan.shard_of(f"ns{k}") != churn_shard
                     for n in ns_nodes[:2]][:64]
        for n in in_scope:
            watchers.append(threading.Thread(
                target=watcher, args=(n, 5.0), daemon=True))
        for n in out_scope:
            watchers.append(threading.Thread(
                target=watcher, args=(n, 1.0), daemon=True))
        for t in watchers:
            t.start()
        time.sleep(0.2)                     # let them park
        workloads.churn_discovery_rule(store, meta, churn_k, 0)
        for t in watchers:
            t.join()
        woken = sum(1 for r in watch_results if r["changed"])
        quiet = sum(1 for r in watch_results if not r["changed"])

        # -- churn storm: scoped invalidation + hit rate -------------
        base = ds._cache.stats()
        churn_targets = sorted(meta["rules_by_ns"])
        invalidated_per_round = []
        for w in range(storm_rounds):
            k = churn_targets[(w * 5) % len(churn_targets)]
            before = ds._cache.stats()["invalidated"]
            workloads.churn_discovery_rule(store, meta, k, w)
            invalidated_per_round.append(
                ds._cache.stats()["invalidated"] - before)
            fleet_poll()
        storm = ds._cache.stats()
        storm_calls = (storm["hits"] - base["hits"]) + \
            (storm["misses"] - base["misses"])
        hit_rate = (storm["hits"] - base["hits"]) / storm_calls \
            if storm_calls else -1.0

        # -- parity vs the unscoped single-node path -----------------
        sample = nodes[:: max(len(nodes) // 12, 1)][:12]
        mismatches = 0
        for n in sample:
            k = meta["ns_of"][idx_of[n] // replicas]
            path = f"/v1/routes/{8000 + k}/istio/{n}"
            if ds._route(path)[0] != ds.reference_bytes(path):
                mismatches += 1
        for ep in ("clusters", "listeners"):
            path = f"/v1/{ep}/istio/{nodes[0]}"
            if ds._route(path)[0] != ds.reference_bytes(path):
                mismatches += 1

        lat = monitor.discovery_latency_snapshot(since=stage_base)
        push = lat["push"]
        view = ds.debug_view()
        return {
            "discovery_sidecars": meta["n_sidecars"],
            "discovery_services": n_services,
            "discovery_namespaces": n_ns,
            "discovery_route_rules": meta["n_routes"],
            "discovery_node_groups": groups,
            "discovery_build_s": round(build_s, 2),
            "discovery_cold_fill_s": round(cold_s, 2),
            "discovery_configs_per_sec": round(rates[1], 1),
            "discovery_configs_per_sec_min": round(rates[0], 1),
            "discovery_configs_per_sec_max": round(rates[-1], 1),
            "discovery_wire_configs_per_sec": round(wire_rate, 1),
            "discovery_wire": "stdlib threaded HTTP front, loopback, "
                              f"{len(wire_nodes)} sequential GETs — "
                              "bounds any wire claim; configs_per_sec "
                              "is the in-process serve path",
            "discovery_cache_hit_rate": round(hit_rate, 4),
            "discovery_churn_rounds": storm_rounds,
            "discovery_invalidated_per_round": invalidated_per_round,
            "discovery_push_watchers": len(watch_results),
            "discovery_push_woken": woken,
            "discovery_push_quiet": quiet,
            "discovery_push_fanout_ms_p50": push.get("p50_ms"),
            "discovery_push_fanout_ms_p99": push.get("p99_ms"),
            "discovery_parity_ok": bool(mismatches == 0),
            "discovery_parity_mismatches": mismatches,
            "discovery_scope_program_rules":
                view["scope_program"]["constrained_rules"],
            "discovery_stage_attribution": lat["stages"],
            "discovery_generation": view["generation"],
        }
    except Exception as exc:
        return {"discovery_error": f"{type(exc).__name__}: {exc}"}
    finally:
        if ds is not None:
            ds.stop()


def _quota_bench(on_tpu: bool) -> dict:
    """BASELINE config 4: memquota 100k-key batched counter eval.

    The serving path's device quota kernel — since r4 the ROLLING-
    window variant (models/quota_alloc.make_rolling_alloc_step;
    reference semantics mixer/adapter/memquota/memquota.go:107-118 +
    rollingWindow.go, quantized to the host adapter's 10 slots per
    window): each step rolls the touched buckets then allocates
    against the live window sum. Four shapes are timed: the
    vectorized step on ~unique buckets (the typical shape at 100k
    live keys), the sequential scan (test/bench parity ORACLE — the
    serving path never selects it), a SKEWED (zipf) key distribution
    at unit amounts (the rank kernel), and the same zipf keys with
    MIXED amounts 1-5 (the segmented prefix-sum kernel — the shape
    that used to stall in the O(B) scan, VERDICT r4 item 4).
    Baseline: the reference's alloc is a mutex'd host map op, ~1 µs
    each single-threaded ⇒ ~1M allocs/s/core."""
    try:
        from istio_tpu.adapters.memquota import _TICKS_PER_WINDOW
        from istio_tpu.models.quota_alloc import make_rolling_alloc_step

        n_keys = 100_000 if on_tpu else 4_096
        n_buckets = 131_072 if on_tpu else 8_192
        batch = 32_768 if on_tpu else 256
        # deep windows: the alloc step is sub-ms, so tunnel sync noise
        # (±20ms per window) must amortize over many steps — at 60 the
        # number still swung 2×; 200 × ~0.3ms ≈ 60ms of real work per
        # window, noise ±0.1ms
        steps = 200 if on_tpu else 5
        rng = np.random.default_rng(5)
        scan, fast, unit, seg = make_rolling_alloc_step(
            n_buckets, _TICKS_PER_WINDOW)
        counts = jax.device_put(jax.numpy.zeros(
            (n_buckets, _TICKS_PER_WINDOW), jax.numpy.int32))
        amounts = jax.device_put(np.ones(batch, np.int32))
        be = jax.device_put(np.zeros(batch, bool))
        mx = jax.device_put(np.full(batch, 1 << 30, np.int32))
        active = jax.device_put(np.ones(batch, bool))
        ticks = jax.device_put(np.full(batch, 7, np.int32))
        lasts = jax.device_put(np.full(batch, 5, np.int32))
        rolling = jax.device_put(np.ones(batch, bool))
        sync_s = _roundtrip_s()

        def timed(fn, counts, buckets, n_steps=None):
            n_steps = n_steps or steps
            buckets = jax.device_put(buckets)
            g, counts = fn(counts, buckets, amounts, be, mx, active,
                           ticks, lasts, rolling)
            jax.block_until_ready(g)
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(n_steps):
                    g, counts = fn(counts, buckets, amounts, be, mx,
                                   active, ticks, lasts, rolling)
                jax.block_until_ready(g)
                ts.append((time.perf_counter() - t0 - sync_s) / n_steps)
            return _med3(ts), counts

        # without replacement: a sampled-with-replacement batch carries
        # ~5k duplicate rows at this size, a shape the serving path
        # routes to the contended kernels, not the fast one
        uniq_buckets = rng.permutation(n_keys)[:batch].astype(np.int32)
        # zipf-skewed keys: the realistic serving distribution (hot
        # users dominate); ~a=1.3 gives heavy head + long tail
        zipf = (rng.zipf(1.3, batch) - 1) % n_keys
        zipf_buckets = zipf.astype(np.int32)
        skew_unique_frac = len(np.unique(zipf_buckets)) / batch

        (t_fast, tf_min, tf_max), counts = timed(fast, counts,
                                                 uniq_buckets)
        (t_scan, _, _), counts = timed(scan, counts, uniq_buckets,
                                       n_steps=max(steps // 16, 2))
        # skewed batches serve through the parallel rank kernel
        # (amount=1, the rate-limit shape)
        (t_skew, _, _), counts = timed(unit, counts, zipf_buckets)
        # contended MIXED amounts (hot keys + amount>1): the shape
        # that used to fall back to the O(B) scan now rides the
        # segmented prefix-sum kernel on the serving path (VERDICT r4
        # item 4); timed on the same zipf keys with amounts 1..5
        amounts = jax.device_put(
            (rng.integers(1, 6, batch)).astype(np.int32))
        (t_mixed, _, _), counts = timed(seg, counts, zipf_buckets)
        baseline = 1e6   # ~1 µs per host alloc (memquota map + mutex)
        cps = batch / t_fast
        return {"quota_keys": n_keys,
                "quota_counter_rows": n_buckets,
                "quota_window_ticks": _TICKS_PER_WINDOW,
                "quota_batch": batch,
                "quota_alloc_step_ms": round(t_fast * 1e3, 3),
                "quota_scan_step_ms": round(t_scan * 1e3, 3),
                "quota_skewed_step_ms": round(t_skew * 1e3, 3),
                "quota_skewed_unique_frac": round(skew_unique_frac, 3),
                "quota_skewed_allocs_per_sec": round(batch / t_skew, 1),
                "quota_mixed_step_ms": round(t_mixed * 1e3, 3),
                "quota_mixed_allocs_per_sec": round(batch / t_mixed, 1),
                "quota_serving_scan_free": True,
                "quota_allocs_per_sec": round(cps, 1),
                "quota_allocs_per_sec_min": round(batch / tf_max, 1),
                "quota_allocs_per_sec_max": round(batch / tf_min, 1),
                "quota_baseline_allocs_per_sec": baseline,
                "quota_vs_baseline": round(cps / baseline, 2)}
    except Exception as exc:
        return {"quota_error": f"{type(exc).__name__}: {exc}"}


def _served_bench(n_rules: int, on_tpu: bool) -> dict:
    """END-TO-END number: real gRPC Check RPCs from external client
    processes through decode → C++ tensorize → device step → response,
    measured at the client (mixer/pkg/perf pattern; VERDICT r1 item 3).

    The axon TPU tunnel adds ~100ms per host↔device sync; the batcher
    pipelines in-flight batches to amortize it, but per-request latency
    carries at least one tunnel round-trip on this rig — the reported
    device_sync_ms field makes that floor explicit (a colocated chip
    syncs in microseconds)."""
    import multiprocessing as mp

    try:
        from istio_tpu.runtime import monitor
        counters0 = monitor.serving_counters()
        resil0 = monitor.resilience_counters()
        forens0 = monitor.forensics_counters()
    except Exception:   # counters are diagnostics, never a crash
        monitor = None
        counters0 = {}
        resil0 = {}
        forens0 = {}

    def resilience_fields() -> dict:
        """Shed / expired / fallback deltas for THIS scenario."""
        if monitor is None:
            return {}
        return {f"served_srv_{k}": v
                for k, v in _resilience_delta(monitor, resil0).items()}

    def counter_fields() -> dict:
        """Server-side counters since this bench began — emitted on
        success AND failure so a failed run is diagnosable from the
        artifact tail (VERDICT r3 weak #1)."""
        if monitor is None:
            return {}
        c = monitor.serving_counters()
        return {
            **resilience_fields(),
            "served_srv_requests_decoded":
                c["requests_decoded"] - counters0["requests_decoded"],
            "served_srv_responses_sent":
                c["responses_sent"] - counters0["responses_sent"],
            "served_srv_in_flight": c["in_flight"],
            "served_srv_batches_formed":
                c["batches_formed"] - counters0["batches_formed"],
            "served_srv_batch_rows":
                c["batch_rows"] - counters0["batch_rows"],
            "served_srv_batch_size_hist": c["batch_size_hist"],
            "served_srv_report_batch_rows":
                c["report_batch_rows"]
                - counters0.get("report_batch_rows", 0),
            "served_srv_report_batches_formed":
                c["report_batches_formed"]
                - counters0.get("report_batches_formed", 0),
        }

    try:
        from istio_tpu.api.grpc_server import MixerAioGrpcServer
        from istio_tpu.runtime import RuntimeServer, ServerArgs
        from istio_tpu.testing import perf, workloads

        sync_ms = _roundtrip_s() * 1e3
        # SHALLOW pipeline behind the tunnel: device trips serialize on
        # the transport (profiled r3: 14 slots fragmented arrivals into
        # ~12-request batches and collapsed throughput 5×; 1-2 slots
        # let the batcher accumulate trip-sized batches — fewer, fatter
        # trips win when trips can't overlap). Colocated chips sync in
        # µs and can go deeper.
        pipeline = 2 if sync_ms > 20 else 8
        store = workloads.make_store(n_rules)
        # bucket ladder sized to the closed-loop equilibrium batch
        # (~cps × trip time): bucket 64 is the LATENCY TIER (sub-ms
        # step at 10k rules — light-load batches stay small and fast),
        # mid buckets avoid both tiny trips and padding a 300-row
        # batch to 2048, and the 2048 ceiling halves trips per client
        # wave when trips serialize on the transport (trips/s × batch
        # IS the served ceiling here)
        buckets = (64, 256, 1024, 2048)
        srv = RuntimeServer(store, ServerArgs(
            initial_prewarm=False,   # plan.prewarm(buckets) below
            batch_window_s=0.002, max_batch=2048, pipeline=pipeline,
            # colocated chips overlap trips for real — let the deep
            # pipeline actually pipeline (hold_at=pipeline); behind
            # the serializing tunnel keep hold_at=1 (fat batches win)
            hold_at=pipeline if sync_ms <= 20 else None,
            buckets=buckets,
            default_manifest=workloads.MESH_MANIFEST))
        n_cores = mp.cpu_count() or 4
        # asyncio front: in-flight checks hold no threads, so the
        # batcher round-trip doesn't cap throughput at workers/RTT
        g = MixerAioGrpcServer(srv)
        try:
            # deterministic warm BEFORE the load window: the initial
            # publish does not prewarm (only config swaps do), and a
            # timed warmup cannot tell whether the multi-second
            # per-bucket compiles actually finished — an unwarmed
            # bucket hit mid-window serializes everything behind a
            # device compile
            plan = srv.controller.dispatcher.fused
            if plan is not None:
                plan.prewarm(buckets)
            port = g.start()
            # every Nth request also allocates a device quota (served
            # quota traffic in the e2e number, VERDICT r2 item 3)
            quota_every = 4
            payloads = perf.make_check_payloads(
                workloads.make_request_dicts(512),
                quota_every=quota_every)
            # closed-loop load: throughput ≤ concurrency / latency, and
            # each request carries ≥1 tunnel RTT (~100ms) on this rig —
            # the pipe only fills with hundreds in flight. Workers
            # pipeline futures, so concurrency is cheap; on a 1-core
            # box extra client processes just steal the server's CPU.
            n_procs = 1 if n_cores <= 2 else min(4, n_cores - 2)
            # closed-loop: cps ≈ concurrency / latency, and behind the
            # serialized tunnel latency ≈ 1-2 trips regardless of
            # depth, so offered load must be deep to fill trip-sized
            # batches (profiled knee ~2k in flight on this rig)
            # completion-counted window (VERDICT r3 item 1): record the
            # next N completions after attach + warmup + steady-state —
            # such a window cannot close empty while the server answers
            # scenario boundary: warmup traffic (incl. any in-band
            # compile) must not pollute the window's live percentiles
            # or its stage decomposition — the baseline token and
            # window reset are taken by run_load's on_go hook AT the
            # go signal (warmup over), not before the run
            sat_box: dict = {}

            def _sat_go() -> None:
                if monitor is not None:
                    monitor.reset_latency_window()
                    sat_box["base"] = monitor.stage_baseline()
            report = perf.run_load(
                f"127.0.0.1:{port}", payloads,
                n_record=10_000 if on_tpu else 500,
                n_procs=n_procs, concurrency=1024 if on_tpu else 32,
                warmup_s=8.0 if on_tpu else 2.0, on_go=_sat_go)
            # stage-level attribution for the saturation window (the
            # introspect /metrics decomposition, scraped in-process):
            # every BENCH from this PR on carries queue_wait /
            # tensorize / h2d / device_step / fold / respond so a perf
            # regression names its stage without a rerun
            sat_stage_fields: dict = {}
            if monitor is not None:
                snap = monitor.latency_snapshot(
                    since=sat_box.get("base"))
                sat_stage_fields = {
                    "served_stage_decomposition": snap["stages"],
                    "served_live_p99_ms": round(
                        snap["live"]["p99_ms"], 2),
                    "served_live_window_n": snap["live"]["n_window"],
                }
                monitor.reset_latency_window()
            # phase 1b — LIGHT load: the latency-relevant regime
            # (saturation p50/p99 above is queueing by Little's law,
            # not service latency). At depth 8 a request's latency ≈
            # one tunnel RTT + the latency-tier step; a colocated
            # chip's floor is the step itself.
            light_fields: dict = {}
            try:
                # ONE worker: the point is the depth-8 regime — extra
                # client processes would each add 8 more in flight.
                # Stage spans captured in-process decompose the p50
                # (VERDICT r4 item 7: 301ms ≈ 2.7 RTT went
                # unexplained; the artifact now itemizes queue-wait /
                # tensorize / device / overlay per batch)
                from istio_tpu.utils import tracing as _tr
                mem, restore = _tr.capture("bench-light")
                t_light0 = time.time()
                light_warm_s = 2.0
                # same on_go discipline as the saturation phase: the
                # server-side window/baseline open when warmup ends,
                # matching the client-side recorded window
                light_box: dict = {}

                def _light_go() -> None:
                    if monitor is not None:
                        monitor.reset_latency_window()
                        light_box["base"] = monitor.stage_baseline()
                try:
                    lreport = perf.run_load(
                        f"127.0.0.1:{port}", payloads,
                        n_record=400 if on_tpu else 100,
                        n_procs=1, concurrency=8,
                        warmup_s=light_warm_s, on_go=_light_go)
                finally:
                    restore()
                # steady-state spans only: the recorded-completion
                # window excludes the warmup ramp, so the stage
                # medians must too (ramp batches run at different
                # sizes/depths than the regime they'd be blamed on)
                t_steady_us = (t_light0 + light_warm_s) * 1e6
                stage: dict = {}
                for span in mem.spans:
                    if span.get("timestamp", 0) < t_steady_us:
                        continue
                    ms = span.get("duration", 0) / 1000.0
                    stage.setdefault(span.get("name"), []).append(ms)
                    qw = (span.get("tags") or {}).get("queue_wait_ms")
                    if qw is not None:
                        stage.setdefault("queue_wait", []).append(
                            float(qw))
                stage_med = {
                    k: round(sorted(v)[len(v) // 2], 2)
                    for k, v in stage.items() if v}
                # the BOUNDED-LATENCY operating point (VERDICT r4 weak
                # #5): depth 8 is the served config whose latency
                # stays near the transport floor — the artifact pins
                # an explicit p99 budget so "bounded" is a checked
                # claim, not a label. Derivation (the stage spans
                # decompose it): trips serialize on this transport, so
                # a quota-carrying request's worst structural path is
                # drain-the-in-flight-trip + own check trip + the NEXT
                # check trip (depth-8 arrivals keep coming, and the
                # quota flush queues behind it) + the quota-flush trip
                # = 4 serialized trips, + 0.5 trip alignment jitter +
                # 10ms host margin; 30ms floor when colocated. The
                # trip time is the WINDOW'S OWN observed serve.batch
                # median — an RTT sampled at bench start drifted 30%
                # from the light phase's real trips and failed the
                # gate spuriously — CAPPED at 1.5x the sampled RTT +
                # 15ms so the gate stays falsifiable: a genuine trip
                # regression blows past the cap and fails on absolute
                # terms instead of self-normalizing away. Observed
                # p99s sit at 3.1-4.0 trips across runs. Saturation
                # numbers above are queueing by Little's law and
                # carry no latency claim.
                trip_ms = min(stage_med.get("serve.batch", sync_ms),
                              1.5 * sync_ms + 15.0)
                light_budget_ms = max(4.5 * trip_ms + 10.0, 30.0)
                # live (server-side) percentile tracker vs the rig's
                # client-side p99 — the acceptance cross-check: the
                # sliding window covers the same light run (reset at
                # phase start), so the two p99s should agree up to
                # wire + decode overhead (<=20% at trip-scale
                # latencies)
                light_live_fields: dict = {}
                if monitor is not None:
                    lsnap = monitor.latency_snapshot(
                        since=light_box.get("base"))
                    live_p99 = lsnap["live"]["p99_ms"]
                    light_live_fields = {
                        "served_light_stage_decomposition":
                            lsnap["stages"],
                        "served_light_live_p99_ms": round(live_p99, 2),
                        "served_light_live_p50_ms": round(
                            lsnap["live"]["p50_ms"], 2),
                        "served_light_live_window_n":
                            lsnap["live"]["n_window"],
                        "served_light_live_p99_agrees":
                            bool(lreport.p99_ms > 0 and
                                 abs(live_p99 - lreport.p99_ms)
                                 <= 0.2 * lreport.p99_ms),
                        "check_p99_under_target":
                            lsnap["live"]["under_target"],
                    }
                light_fields = {
                    "served_light_stage_p50_ms": stage_med,
                    **light_live_fields,
                    "served_light_checks_per_sec": round(
                        lreport.checks_per_sec, 1),
                    "served_light_p50_ms": round(lreport.p50_ms, 2),
                    "served_light_p99_ms": round(lreport.p99_ms, 2),
                    "served_light_p99_budget_ms": round(
                        light_budget_ms, 1),
                    "served_light_p99_budget_ok":
                        bool(lreport.p99_ms <= light_budget_ms),
                    "served_light_budget_derivation":
                        "4 serialized trips (drain in-flight + own "
                        "check + interleaved next check + quota flush)"
                        " + 0.5 trip jitter + 10ms; trip = this "
                        "window's observed serve.batch median, capped "
                        "at 1.5x sampled RTT + 15ms so a real trip "
                        "regression still fails the gate",
                    "served_light_trip_ms": round(trip_ms, 1),
                    "served_light_clients": "1x8",
                    "served_light_errors": lreport.n_errors,
                    "served_light_first_error": lreport.first_error,
                    "served_light_truncated": lreport.truncated,
                }
            except Exception as exc:
                light_fields = {"served_light_error":
                                f"{type(exc).__name__}: {exc}"}
            # phase 2 — the shim protocol (mixer.proto BatchCheck): one
            # RPC carries a bucket-sized batch of independent bags, so
            # the ~0.4ms/RPC python-grpc cost (see
            # served_grpc_ceiling_per_sec) is paid once per batch. This
            # is the transport a colocated C++ sidecar shim actually
            # uses (SURVEY §2.9 implication (a)).
            bsz = 1024 if on_tpu else 64
            batched_fields: dict = {}
            try:
                bpayloads = perf.make_batch_check_payloads(
                    workloads.make_request_dicts(512), batch_size=bsz)
                breport = perf.run_load(
                    f"127.0.0.1:{port}", bpayloads,
                    n_record=48 if on_tpu else 12,
                    n_procs=n_procs, concurrency=3,
                    warmup_s=4.0 if on_tpu else 1.0,
                    method="/istio.mixer.v1.Mixer/BatchCheck",
                    checks_per_payload=bsz)
                batched_fields = {
                    "served_batched_checks_per_sec": round(
                        breport.checks_per_sec, 1),
                    "served_batched_batch_size": bsz,
                    "served_batched_rpc_p50_ms": round(breport.p50_ms, 2),
                    "served_batched_rpc_p99_ms": round(breport.p99_ms, 2),
                    "served_batched_errors": breport.n_errors,
                    "served_batched_first_error": breport.first_error,
                }
            except Exception as exc:   # keep the unary phase's results
                batched_fields = {"served_batched_error":
                                  f"{type(exc).__name__}: {exc}"}
            # phase 3 — the REPORT path (grpcServer.go:262; the
            # reference's report benchmarks are unpublished,
            # mixer/test/perf/singlereport_test.go): batched records
            # through gRPC → delta decode → fused resolve (ONE packed
            # device trip per RPC, record counts padded to the
            # prewarmed serving buckets) → metric adapter fan-out on
            # the host.
            report_fields: dict = {}
            try:
                # ≥1024 records per RPC (ROADMAP item 1 first slice /
                # ISSUE 6 satellite): the report batcher coalesces
                # records across RPCs into bucket-sized packed device
                # trips either way, but fat RPCs stop paying the
                # ~0.4ms python-grpc cost 16× per bucket — at 64
                # records/RPC the wire front, not the device lowering,
                # capped records/s
                rsz = 1024 if on_tpu else 256
                rpayloads = perf.make_report_payloads(
                    workloads.make_request_dicts(512),
                    records_per_request=rsz)
                # ingestion-plane accounting for THIS phase: report
                # stage decomposition + record conservation, deltaed
                # against the phase's own baseline (the counters are
                # process-cumulative)
                rcons0 = monitor.report_conservation() \
                    if monitor is not None else None
                rstage0 = monitor.report_stage_baseline() \
                    if monitor is not None else None
                # depth-8 clients put 8192 records in flight so the
                # 2048-row bucket fills several trips deep
                rrep = perf.run_load(
                    f"127.0.0.1:{port}", rpayloads,
                    n_record=48 if on_tpu else 8,
                    n_procs=1, concurrency=8 if on_tpu else 4,
                    warmup_s=2.0 if on_tpu else 1.0,
                    method="/istio.mixer.v1.Mixer/Report",
                    checks_per_payload=rsz)
                # per-record baseline, derived (the reference's report
                # numbers are unpublished): its dispatcher resolves the
                # FULL ruleset per record-bag before instance build
                # (runtime/dispatcher.go report dispatch), and one
                # predicate costs 164-586 ns on the Go IL interpreter
                # (bench.baseline:3-8) — at the mid 250 ns and
                # n_rules rules a record costs n_rules*250ns of pure
                # resolve (2.5 ms @10k) before its ~6 field exprs
                # (~1.5 µs, negligible at this scale).
                base_rps = 1.0 / (n_rules * 250e-9)
                report_fields = {
                    "served_report_records_per_sec": round(
                        rrep.checks_per_sec, 1),
                    "served_report_records_per_rpc": rsz,
                    "served_report_baseline_records_per_sec": round(
                        base_rps, 1),
                    "served_report_vs_baseline": round(
                        rrep.checks_per_sec / base_rps, 2),
                    "served_report_baseline_derivation":
                        f"{n_rules} rules x 250ns/predicate IL resolve "
                        "per record-bag (bench.baseline:3-8)",
                    "served_report_rpc_p50_ms": round(rrep.p50_ms, 2),
                    "served_report_errors": rrep.n_errors,
                    "served_report_first_error": rrep.first_error,
                }
                if monitor is not None:
                    # drain before judging conservation: the grpc
                    # front blocks per RPC, but the coalescer may
                    # still hold the last window's records
                    rcons = None
                    t_dl = time.time() + 30.0
                    while time.time() < t_dl:
                        rcons = monitor.report_conservation(
                            since=rcons0)
                        if rcons["in_flight"] == 0:
                            break
                        time.sleep(0.05)
                    report_fields["served_report_stage_"
                                  "decomposition"] = \
                        monitor.report_latency_snapshot(
                            since=rstage0)["stages"]
                    report_fields["served_report_conservation"] = \
                        rcons
                    report_fields["served_report_conservation_"
                                  "exact"] = bool(
                        rcons is not None and rcons["exact"]
                        and rcons["in_flight"] == 0)
            except Exception as exc:
                report_fields = {"served_report_error":
                                 f"{type(exc).__name__}: {exc}"}
            # rule-telemetry cost for THIS served scenario (ISSUE 4
            # acceptance: accumulators-on vs off + drain wall)
            tele_fields = _telemetry_overhead_fields(srv, "served_")
            # tail forensics for THIS served scenario (ISSUE 14):
            # stage skew attribution + exemplar/event window counts +
            # recorder-on-vs-off overhead
            tail_fields = {
                **_tail_fields("served_",
                               sat_stage_fields.get(
                                   "served_stage_decomposition"),
                               forens0),
                **_forensics_overhead_fields(srv, "served_"),
                **_audit_fields(srv, "served_"),
            }
        finally:
            g.stop()
            srv.close()
        return {
            "served_checks_per_sec": round(report.checks_per_sec, 1),
            "served_p50_ms": round(report.p50_ms, 2),
            "served_p99_ms": round(report.p99_ms, 2),
            "served_n_requests": report.n_requests,
            "served_errors": report.n_errors,
            "served_window_s": round(report.duration_s, 2),
            "served_warmup_completions": report.warmup_completions,
            "served_steady_rate_per_sec": round(
                report.steady_rate_per_sec, 1),
            "served_truncated": report.truncated,
            "served_first_error": report.first_error,
            "served_clients": f"{report.n_procs}x{report.concurrency}",
            "served_quota_frac": round(1.0 / quota_every, 3),
            **sat_stage_fields,
            **light_fields,
            **batched_fields,
            **report_fields,
            **tele_fields,
            **tail_fields,
            "device_sync_ms": round(sync_ms, 1),
            **_grpc_ceiling_fields(),
            **counter_fields(),
        }
    except Exception as exc:   # the device-step numbers must still print
        return {"served_error": f"{type(exc).__name__}: {exc}",
                **counter_fields()}


def _served_native_bench(n_rules: int, on_tpu: bool) -> dict:
    """The NATIVE front-end at the REAL unary wire (VERDICT r4 item 1):
    C++ HTTP/2+HPACK+gRPC server (native/httpd.cpp) terminating
    istio.mixer.v1.Mixer/Check, C++ closed-loop client
    (native/h2load.cpp) — the python grpc stack appears nowhere, so
    the measured number is engine + transport, not interpreter. Every
    4th request carries a quota (same mix as the grpc phases; quota
    rows complete via pool-future callbacks without stalling their
    batch-mates).

    Variance honesty (VERDICT r4 item 5): the saturation number is
    median/min/max over 3 back-to-back windows, judged on the median.
    """
    try:
        from istio_tpu.api.native_server import (NativeMixerServer,
                                                 start_echo_server)
        from istio_tpu.runtime import RuntimeServer, ServerArgs
        from istio_tpu.testing import perf, workloads

        buckets = (64, 256, 1024, 2048) if on_tpu else (64, 256)
        # depth 2x the top bucket: half the in-flight rows ride the
        # current trip, the other half fill the next batch (measured
        # +30% over depth=bucket on the serialized tunnel)
        depth = 4096 if on_tpu else 64
        store = workloads.make_store(n_rules)
        srv = RuntimeServer(store, ServerArgs(
            initial_prewarm=False,   # plan.prewarm(buckets) below
            batch_window_s=0.002, max_batch=buckets[-1], pipeline=2,
            buckets=buckets,
            # check-cache grants ON: the native scenario measures the
            # full latency plane incl. the grant-derived TTLs the
            # client-cache phase below exercises (age-quantized, so
            # the response memo stays effective)
            check_grants=True,
            default_manifest=workloads.MESH_MANIFEST))
        # min_fill ~ half the ceiling bucket: behind the serialized
        # tunnel the equilibrium batch is ~cps/trips_per_sec; holding
        # for a full 2048 would idle the transport at moderate load
        native = NativeMixerServer(
            srv, max_batch=buckets[-1],
            min_fill=1024 if on_tpu else 32,
            window_us=50_000 if on_tpu else 2_000, pumps=2)
        try:
            plan = srv.controller.dispatcher.fused
            if plan is not None:
                plan.prewarm(buckets)
            port = native.start()
            try:
                from istio_tpu.runtime import monitor as _mon
                _mon.reset_latency_window()
                native_stage_base = _mon.stage_baseline()
                native_resil0 = _mon.resilience_counters()
                native_forens0 = _mon.forensics_counters()
            except Exception:
                _mon, native_stage_base = None, None
                native_resil0 = {}
                native_forens0 = {}
            dicts = workloads.make_request_dicts(512)
            payloads = perf.make_check_payloads(dicts, quota_every=4)

            def h2(pay, n, d, warm, tag,
                   method="/istio.mixer.v1.Mixer/Check"):
                # one retry per phase: a single tunnel hiccup (poll
                # timeout) must not wipe a section whose other phases
                # measured fine (r5: the whole native artifact once
                # died on a transient in the depth-8 phase)
                try:
                    return perf.run_h2load(port, pay, n, d, warm,
                                           method=method)
                except Exception as exc:
                    phase_errors[tag] = f"{type(exc).__name__}: {exc}"
                    return perf.run_h2load(port, pay, n, d, warm,
                                           method=method)

            phase_errors: dict = {}
            # warm the serving path (quota pools, memo, code paths)
            h2(payloads, 1000 if on_tpu else 100, depth, 2.0, "warm")

            def wire_windows(native_srv, run_window, n_windows=3):
                """Run `n_windows` closed-loop windows, reading the
                C++ wire histogram around each — returns (client
                reps, per-window wire latency snapshots). The wire
                snapshot is the SERVER-side per-request truth (frame
                decode → response write); the client rep is the
                independent cross-check."""
                rs, ws = [], []
                for i in range(n_windows):
                    base = native_srv.latency_raw()
                    rs.append(run_window(i))
                    ws.append(native_srv.latency_snapshot(since=base))
                return rs, ws

            # ≥1.3s windows: at ~9k/s a 6000-completion window closed
            # in ~0.7s and single tunnel stalls swung the min window
            # ~2x — completion counts sized so stalls amortize
            reps, sat_wires = wire_windows(
                native,
                lambda i: h2(payloads, 12000 if on_tpu else 300,
                             depth, 0.5, f"sat{i}"))
            # the MEDIAN-throughput window supplies BOTH the headline
            # cps and its latencies — mixing windows would pair a
            # median rate with an outlier window's p50/p99
            def median_window(rs):
                """(median rep, min cps, max cps, total errors) — the
                single variance-doctrine reduction for 3-window
                phases."""
                srt = sorted(rs, key=lambda r: r["checks_per_sec"])
                return (srt[len(srt) // 2],
                        srt[0]["checks_per_sec"],
                        srt[-1]["checks_per_sec"],
                        sum(r["errors"] for r in rs))

            med_rep, cps_min, cps_max, sat_errors = median_window(reps)
            # no-quota window: every trip the quota mix costs is a
            # POOL-FLUSH trip serialized between check trips (25% of
            # rows carry quota → ~1:1 trip ratio, halving the rate);
            # this field pins the pure-check wire rate so the gap is
            # attributed to the quota protocol, not the engine
            stubbed: list = []
            nq_payloads = perf.make_check_payloads(dicts,
                                                   quota_every=0)
            try:
                # same variance doctrine as the sat phases: 3 windows,
                # judged on the median, each ≥1.3s at the ~2x no-quota
                # rate (hence 2x the completions per window, both
                # branches)
                nq_reps = [h2(nq_payloads, 24000 if on_tpu else 600,
                              depth, 0.5, f"noquota{i}")
                           for i in range(3)]
                nqrep, nq_min, nq_max, nq_errors = \
                    median_window(nq_reps)
            except Exception as exc:
                phase_errors["noquota-final"] = \
                    f"{type(exc).__name__}: {exc}"
                stubbed.append("noquota")
                nqrep = {"checks_per_sec": -1.0, "p50_ms": -1.0}
                nq_min = nq_max = -1.0
                nq_errors = -1
            # light load: depth 8 — the latency regime (saturation
            # p50/p99 is queueing, not service time). Wire-histogram
            # delta captured alongside: this is the regime where the
            # batching policy (occupancy hold vs continuous) IS the
            # latency, so the policy comparison below is judged here.
            try:
                light_base = native.latency_raw()
                lrep = h2(payloads, 300 if on_tpu else 100, 8, 2.0,
                          "light")
                light_wire = native.latency_snapshot(
                    since=light_base)
            except Exception as exc:
                # the light phase is informative, not the headline —
                # never let it take the saturation numbers down; its
                # fields are explicitly marked fabricated below
                phase_errors["light-final"] = \
                    f"{type(exc).__name__}: {exc}"
                stubbed.append("light")
                # -1.0 sentinels, never 0.0: a fabricated zero reads
                # as a real measurement (perf.PerfError invariant)
                lrep = {"checks_per_sec": -1.0, "p50_ms": -1.0,
                        "p99_ms": -1.0}
                light_wire = {"p50": -1.0, "p99": -1.0}
            # phase — REPORT at the native wire (ROADMAP item 1 / the
            # telemetry ingestion plane): ReportRequests through the
            # C++ front, records ack-after-enqueue into the cross-RPC
            # coalescer, instance fields evaluated on device via
            # packed_report. records/s = RPC completions/s × records
            # per RPC (the client counts RPC completions; every acked
            # RPC's records are conservation-accounted server-side —
            # the exactness check below proves none were dropped
            # behind the ack). Median of 3 windows, same variance
            # doctrine as the Check phases.
            nrep_fields: dict = {}
            try:
                rsz = 1024 if on_tpu else 128
                rpayloads = perf.make_report_payloads(
                    dicts, records_per_request=rsz)
                rcons0 = _mon.report_conservation() \
                    if _mon is not None else None
                rstage0 = _mon.report_stage_baseline() \
                    if _mon is not None else None
                h2(rpayloads, 40 if on_tpu else 6,
                   16 if on_tpu else 4, 1.0, "report-warm",
                   method="/istio.mixer.v1.Mixer/Report")

                # the headline is the EXPORT rate (records whose
                # adapter dispatch completed), NOT acked-RPCs × size:
                # ack-after-enqueue acks at admission, so a closed-
                # loop client saturates the bounded coalescer and the
                # overflow sheds typed RESOURCE_EXHAUSTED — counting
                # acked records would credit shed ones. Export deltas
                # over each window's wall are the sustained truth.
                def report_window(i: int) -> dict:
                    e0 = _mon.report_conservation()["exported"] \
                        if _mon is not None else 0
                    t0 = time.time()
                    r = h2(rpayloads, 200 if on_tpu else 24,
                           16 if on_tpu else 4, 0.3, f"report{i}",
                           method="/istio.mixer.v1.Mixer/Report")
                    wall = max(time.time() - t0, 1e-9)
                    e1 = _mon.report_conservation()["exported"] \
                        if _mon is not None else 0
                    r["exported_records_per_sec"] = \
                        (e1 - e0) / wall if _mon is not None \
                        else r["checks_per_sec"] * rsz
                    return r

                nreps = [report_window(i) for i in range(3)]
                srt = sorted(nreps,
                             key=lambda r: r["exported_records_per_sec"])
                rrep = srt[len(srt) // 2]
                r_min = srt[0]["exported_records_per_sec"]
                r_max = srt[-1]["exported_records_per_sec"]
                r_errors = sum(r["errors"] for r in nreps)
                # drain: the ack races the export by design — wait
                # out in_flight before judging conservation (bounded;
                # a wedged drain shows as exact=False, never a hang)
                rcons = None
                if _mon is not None:
                    deadline = time.time() + 30.0
                    while time.time() < deadline:
                        rcons = _mon.report_conservation(since=rcons0)
                        if rcons["in_flight"] == 0:
                            break
                        time.sleep(0.05)
                # per-record baseline, derived like the grpc report
                # phase: the reference resolves the FULL ruleset per
                # record-bag before instance build, ~250ns/predicate
                # on the Go IL interpreter (bench.baseline:3-8)
                base_rps = 1.0 / (n_rules * 250e-9)
                exp_rate = rrep["exported_records_per_sec"]
                nrep_fields = {
                    "served_native_report_records_per_sec": round(
                        exp_rate, 1),
                    "served_native_report_records_per_sec_min": round(
                        r_min, 1),
                    "served_native_report_records_per_sec_max": round(
                        r_max, 1),
                    "served_native_report_windows": 3,
                    "served_native_report_records_per_rpc": rsz,
                    "served_native_report_acked_rpcs_per_sec": round(
                        rrep["checks_per_sec"], 1),
                    "served_native_report_rpc_p50_ms": round(
                        rrep["p50_ms"], 2),
                    # typed sheds (RESOURCE_EXHAUSTED acks) — overload
                    # behavior, not failures; the conservation block
                    # below carries the rejected-record counts
                    "served_native_report_rejected_rpcs": r_errors,
                    "served_native_report_rate_derivation":
                        "exported-record deltas / window wall "
                        "(ack-after-enqueue: acked != exported under "
                        "closed-loop overload; sheds are typed and "
                        "conservation-counted)",
                    "served_native_report_baseline_records_per_sec":
                        round(base_rps, 1),
                    "served_native_report_vs_baseline": round(
                        exp_rate / base_rps, 2),
                    "served_native_report_baseline_derivation":
                        f"{n_rules} rules x 250ns/predicate IL "
                        "resolve per record-bag (bench.baseline:3-8)",
                }
                if _mon is not None:
                    nrep_fields["served_native_report_stage_"
                                "decomposition"] = \
                        _mon.report_latency_snapshot(
                            since=rstage0)["stages"]
                    nrep_fields["served_native_report_conservation"] \
                        = rcons
                    nrep_fields["served_native_report_conservation_"
                                "exact"] = bool(
                        rcons is not None and rcons["exact"]
                        and rcons["in_flight"] == 0)
            except Exception as exc:
                nrep_fields = {"served_native_report_error":
                               f"{type(exc).__name__}: {exc}"}
            counters = native.counters()
            # stage decomposition for THIS scenario only (delta vs the
            # baseline taken at server start — the histograms are
            # process-cumulative and the grpc section ran first): the
            # native pump drives the same fused path, so h2d /
            # device_step / fold / respond attribute its windows
            try:
                stage_fields = {
                    "served_native_stage_decomposition":
                        _mon.latency_snapshot(
                            since=native_stage_base)["stages"]} \
                    if _mon is not None else {}
                if _mon is not None:
                    # overload behavior for THIS scenario (shed /
                    # expired / fallback deltas)
                    stage_fields["served_native_resilience"] = \
                        _resilience_delta(_mon, native_resil0)
                    _mon.reset_latency_window()
            except Exception:
                stage_fields = {}
            tele_fields = _telemetry_overhead_fields(
                srv, "served_native_")
            # tail forensics for the native scenario (ISSUE 14): the
            # skew attribution reads the same stage delta computed
            # above; overhead A/B rides the in-process path
            tail_fields = {
                **_tail_fields("served_native_",
                               stage_fields.get(
                                   "served_native_stage_"
                                   "decomposition"),
                               native_forens0),
                **_forensics_overhead_fields(srv, "served_native_"),
                **_audit_fields(srv, "served_native_"),
            }

            # -- measured wire-to-verdict p99 (the tentpole number) --
            # occupancy-fill per-window wire p99s (the server config
            # the throughput phases ran under)
            def wire_p99_spread(ws):
                ps = sorted(w.get("p99", 0.0) for w in ws)
                return (ps[len(ps) // 2], ps[0], ps[-1]) if ps \
                    else (-1.0, -1.0, -1.0)

            occ_p99, occ_p99_min, occ_p99_max = \
                wire_p99_spread(sat_wires)
            lat_fields: dict = {
                "served_native_occupancy_p99_ms": round(occ_p99, 3),
                "served_native_occupancy_p99_ms_min": round(
                    occ_p99_min, 3),
                "served_native_occupancy_p99_ms_max": round(
                    occ_p99_max, 3),
            }
            # continuous-batching lane: same runtime, same depth, the
            # C++ take policy flipped to the latency lane — measured
            # in the SAME bench run so the p99 comparison is apples
            # to apples (ISSUE 13 acceptance)
            native.stop()
            native2 = NativeMixerServer(
                srv, max_batch=buckets[-1],
                min_fill=1024 if on_tpu else 32,
                window_us=50_000 if on_tpu else 2_000, pumps=2,
                continuous=True)
            try:
                port = native2.start()
                h2(payloads, 500 if on_tpu else 100, depth, 1.0,
                   "cont-warm")
                c_reps, c_wires = wire_windows(
                    native2,
                    lambda i: h2(payloads, 12000 if on_tpu else 300,
                                 depth, 0.5, f"cont{i}"))
                c_p99, c_p99_min, c_p99_max = wire_p99_spread(c_wires)
                c_med = sorted(
                    c_reps,
                    key=lambda r: r["checks_per_sec"])[len(c_reps)//2]
                c_p50s = sorted(w.get("p50", 0.0) for w in c_wires)
                lat_fields.update({
                    # THE measured number: per-request wire-to-verdict
                    # p99 under closed-loop load, median window with
                    # min/max spread, measured entirely in C++ (frame
                    # decode → response frame write)
                    "served_native_check_p99_ms": round(c_p99, 3),
                    "served_native_check_p99_ms_min": round(
                        c_p99_min, 3),
                    "served_native_check_p99_ms_max": round(
                        c_p99_max, 3),
                    "served_native_check_p50_ms": round(
                        c_p50s[len(c_p50s) // 2], 3),
                    "served_native_check_p99_windows": len(c_wires),
                    "served_native_check_p99_method":
                        "C++ wire histogram (frame decode → response "
                        "frame write, 2^(1/8) log buckets), delta per "
                        "closed-loop window (the delta covers the "
                        "client's warmup lead-in too — server-side "
                        "truth for the whole window), judged on the "
                        "median window; continuous-batching lane",
                    # independent client-side cross-check: h2load's
                    # exact per-request latency vector, own clock
                    "served_native_check_p99_client_ms": round(
                        c_med.get("p99_ms", -1.0), 3),
                    "served_native_check_p95_client_ms": round(
                        c_med.get("p95_ms", -1.0), 3),
                    "served_native_continuous_checks_per_sec": round(
                        c_med["checks_per_sec"], 1),
                    "served_native_continuous_depth": depth,
                    # saturation-depth ratio: the policies CONVERGE
                    # at saturation (batches fill instantly either
                    # way) — reported for completeness, judged below
                    # in the light regime where the hold policy IS
                    # the latency
                    "served_native_continuous_sat_p99_ratio": round(
                        occ_p99 / c_p99, 2) if c_p99 > 0 else -1.0,
                })
                # light regime under the continuous lane: the
                # apples-to-apples policy comparison (occupancy held
                # depth-8 arrivals for min_fill/window; continuous
                # dispatches the moment a step slot frees)
                cl_base = native2.latency_raw()
                clrep = h2(payloads, 300 if on_tpu else 100, 8, 2.0,
                           "cont-light")
                cl_wire = native2.latency_snapshot(since=cl_base)
                occ_l_p99 = light_wire.get("p99", -1.0)
                c_l_p99 = cl_wire.get("p99", -1.0)
                lat_fields.update({
                    "served_native_light_occupancy_p99_ms": round(
                        occ_l_p99, 3),
                    "served_native_light_continuous_p99_ms": round(
                        c_l_p99, 3),
                    "served_native_light_continuous_p50_ms": round(
                        cl_wire.get("p50", -1.0), 3),
                    "served_native_light_continuous_client_p99_ms":
                        round(clrep.get("p99_ms", -1.0), 3),
                    # measured continuous-vs-occupancy improvement in
                    # the same run (acceptance: continuous batching
                    # shows measured p99 improvement vs the
                    # occupancy-fill batcher), judged at the latency
                    # regime's depth where the hold policy is the
                    # tail
                    "served_native_continuous_p99_improvement": round(
                        occ_l_p99 / c_l_p99, 2)
                    if c_l_p99 > 0 and occ_l_p99 > 0 else -1.0,
                })

                # -- check-cache grant phase: repeat traffic through a
                # caching MixerClient against the live native front —
                # the hit rate is the fraction of client checks that
                # never crossed the wire (server grants fund it)
                try:
                    from istio_tpu.api.client import MixerClient
                    gclient = MixerClient(f"127.0.0.1:{port}",
                                          enable_check_cache=True)
                    try:
                        gdicts = dicts[:16]
                        for d in gdicts:       # prime the cache
                            gclient.check(d)
                        w0 = native2.counters()["requests_decoded"]
                        n_checks = 3000 if on_tpu else 1200
                        t_g0 = time.time()
                        for i in range(n_checks):
                            gclient.check(gdicts[i % len(gdicts)])
                        g_wall = time.time() - t_g0
                        wire_reqs = (native2.counters()
                                     ["requests_decoded"] - w0)
                        lat_fields.update({
                            "served_native_grant_hit_rate": round(
                                1.0 - wire_reqs / max(n_checks, 1),
                                4),
                            "served_native_grant_checks": n_checks,
                            "served_native_grant_wire_requests":
                                int(wire_reqs),
                            "served_native_grant_distinct_signatures":
                                len(gdicts),
                            "served_native_grant_phase_wall_s": round(
                                g_wall, 2),
                            "served_native_grant_client_stats":
                                dict(gclient.cache_stats),
                            "served_native_grant_policy":
                                srv.grants.stats()
                                if srv.grants is not None else None,
                        })
                    finally:
                        gclient.close()
                except Exception as exc:
                    phase_errors["grants"] = \
                        f"{type(exc).__name__}: {exc}"
            except Exception as exc:
                phase_errors["continuous-final"] = \
                    f"{type(exc).__name__}: {exc}"
                stubbed.append("continuous")
                lat_fields.setdefault("served_native_check_p99_ms",
                                      -1.0)
            finally:
                native2.stop()
        finally:
            native.stop()
            srv.close()

        # pure-wire ceiling: echo mode (C++ responds, no engine) — the
        # bound the engine-side number should be judged against
        eport, estop = start_echo_server()
        try:
            erep = perf.run_h2load(eport, payloads, 20000, 256, 0.5)
        except Exception as exc:   # ceiling is context, not headline
            phase_errors["echo"] = f"{type(exc).__name__}: {exc}"
            stubbed.append("echo")
            erep = {"checks_per_sec": -1.0, "p50_ms": -1.0}
        finally:
            estop()

        hist = counters.pop("batch_size_hist", {})
        return {
            "served_native_checks_per_sec": round(
                med_rep["checks_per_sec"], 1),
            "served_native_checks_per_sec_min": round(cps_min, 1),
            "served_native_checks_per_sec_max": round(cps_max, 1),
            "served_native_windows": 3,
            "served_native_p50_ms": round(med_rep["p50_ms"], 2),
            "served_native_p99_ms": round(med_rep["p99_ms"], 2),
            "served_native_depth": depth,
            "served_native_errors": sat_errors,
            "served_native_quota_frac": 0.25,
            "served_native_noquota_checks_per_sec": round(
                nqrep["checks_per_sec"], 1),
            "served_native_noquota_checks_per_sec_min": round(
                nq_min, 1),
            "served_native_noquota_checks_per_sec_max": round(
                nq_max, 1),
            "served_native_noquota_errors": nq_errors,
            "served_native_noquota_p50_ms": round(nqrep["p50_ms"], 2),
            "served_native_light_checks_per_sec": round(
                lrep["checks_per_sec"], 1),
            "served_native_light_p50_ms": round(lrep["p50_ms"], 2),
            "served_native_light_p99_ms": round(lrep["p99_ms"], 2),
            "served_native_light_depth": 8,
            "served_native_wire_ceiling_per_sec": round(
                erep["checks_per_sec"], 1),
            "served_native_wire_ceiling_p50_ms": round(
                erep["p50_ms"], 3),
            "served_native_srv": counters,
            "served_native_batch_hist": hist,
            **lat_fields,
            **nrep_fields,
            **stage_fields,
            **tele_fields,
            **tail_fields,
            # phase_errors: failures during a phase (retried once,
            # except the *-final entries whose retry also failed) —
            # phases listed in served_native_stubbed_phases emit -1.0
            # sentinel fields, never a fabricated measurement
            **({"served_native_phase_errors": phase_errors}
               if phase_errors else {}),
            **({"served_native_stubbed_phases": stubbed}
               if stubbed else {}),
        }
    except Exception as exc:
        return {"served_native_error": f"{type(exc).__name__}: {exc}"}


def _grpc_ceiling_fields() -> dict:
    """Measure the box's python-grpc loopback ceiling (echo handler, no
    policy work) with the same client rig — served numbers are bounded
    by this structurally; reporting it keeps 'transport-bound' an
    evidenced claim instead of an excuse."""
    try:
        from istio_tpu.testing import perf, workloads
        from istio_tpu.testing.echo import start_echo_server

        port, stop = start_echo_server()
        try:
            payloads = perf.make_check_payloads(
                workloads.make_request_dicts(64))
            rep = perf.run_load(f"127.0.0.1:{port}", payloads,
                                n_record=3000, n_procs=1,
                                concurrency=256, warmup_s=1.0)
        finally:
            stop()
        return {"served_grpc_ceiling_per_sec": round(
            rep.checks_per_sec, 1)}
    except Exception as exc:
        return {"served_grpc_ceiling_error":
                f"{type(exc).__name__}: {exc}"}


def _secure_bench(on_tpu: bool) -> dict:
    """Secure serving plane cost ledger (ISSUE 20): the SAME closed-
    loop check window through a plaintext front and a strict-mTLS
    front off ONE runtime — interleaved paired windows, median-of-3
    (the telemetry-ledger method) — yielding the mTLS per-request
    overhead pct, plus the TLS handshake cost a FRESH connection pays
    (first check minus the steady-state per-check median) and its
    amortization horizon on a persistent connection. The mTLS leg
    includes identity injection (peer SPIFFE SAN folded into the wire
    bag) — that re-encode is part of the honest secure-plane cost.
    Fail-soft: a rig without a PKI backend — or any measurement
    error — emits a note, never takes the artifact down."""
    prefix = "secure_"
    from concurrent import futures as _futures
    try:
        from istio_tpu.secure.backend import available_backends
        if not available_backends():
            return {prefix + "note":
                    "no PKI backend (cryptography or the openssl "
                    "CLI) — secure bench skipped"}
        from istio_tpu.api.client import MixerClient
        from istio_tpu.api.grpc_server import MixerGrpcServer
        from istio_tpu.runtime import RuntimeServer, ServerArgs
        from istio_tpu.secure.mtls import ServingCerts
        from istio_tpu.security import IstioCA, pki, spiffe_id
        from istio_tpu.testing import workloads

        n_rules = 256 if on_tpu else 64
        workers = 4
        per_worker = 32
        window_checks = workers * per_worker

        ca = IstioCA.new_self_signed({})
        root = ca.get_root_certificate()
        skey = pki.generate_key()
        certs = ServingCerts(
            pki.key_to_pem(skey),
            ca.sign(pki.generate_csr(
                skey, spiffe_id("istio-system", "mixer"),
                dns_names=("mixer.local",))),
            root)
        wkey = pki.generate_key()
        wkey_pem = pki.key_to_pem(wkey)
        wcert = ca.sign(pki.generate_csr(
            wkey, spiffe_id("default", "bench")))

        reqs = workloads.make_request_dicts(per_worker)
        srv = RuntimeServer(workloads.make_store(n_rules), ServerArgs(
            batch_window_s=0.001, max_batch=256,
            default_manifest=workloads.MESH_MANIFEST))
        plain = MixerGrpcServer(srv, tls=None)
        strict = MixerGrpcServer(srv, tls=certs, mtls_mode="strict")
        clients: list = []
        pool = _futures.ThreadPoolExecutor(workers)
        try:
            p_port = plain.start()
            s_port = strict.start()

            def mk_mtls():
                return MixerClient(f"127.0.0.1:{s_port}",
                                   enable_check_cache=False,
                                   root_cert_pem=root,
                                   key_pem=wkey_pem, cert_pem=wcert,
                                   server_name="mixer.local")

            def window(cls) -> float:
                """One closed-loop window: `workers` persistent
                connections each drive `per_worker` sequential
                checks. Returns wall seconds."""
                t0 = time.perf_counter()
                list(pool.map(
                    lambda cl: [cl.check(r) for r in reqs], cls))
                return time.perf_counter() - t0

            cls_plain = [MixerClient(f"127.0.0.1:{p_port}",
                                     enable_check_cache=False)
                         for _ in range(workers)]
            cls_mtls = [mk_mtls() for _ in range(workers)]
            clients += cls_plain + cls_mtls
            window(cls_plain)       # warm: jit, memo paths, sessions
            window(cls_mtls)
            plain_ts, mtls_ts = [], []
            for _ in range(3):      # interleave so drift hits both
                plain_ts.append(window(cls_plain))
                mtls_ts.append(window(cls_mtls))
            p_med = _med3(plain_ts)[0]
            m_med = _med3(mtls_ts)[0]
            overhead = (m_med - p_med) / p_med * 100.0 \
                if p_med > 0 else 0.0

            # handshake: a fresh mTLS connection's first check pays
            # TCP + TLS1.3 mutual handshake + cert verification on
            # top of one steady-state check
            per_req_ms = m_med / window_checks * 1e3
            hs = []
            for _ in range(3):
                cl = mk_mtls()
                t0 = time.perf_counter()
                cl.check(reqs[0])
                hs.append(time.perf_counter() - t0)
                cl.close()
            hs_med = _med3(hs)[0] * 1e3
            handshake_ms = max(hs_med - per_req_ms, 0.0)
            # persistent-connection horizon: requests after which the
            # one-time handshake is <1% of cumulative serving time
            amortize = int(handshake_ms / (0.01 * per_req_ms)) \
                if per_req_ms > 0 else 0
            return {
                prefix + "plain_checks_per_sec":
                    round(window_checks / p_med, 1),
                prefix + "mtls_checks_per_sec":
                    round(window_checks / m_med, 1),
                prefix + "mtls_overhead_pct": round(overhead, 2),
                prefix + "plain_window_s":
                    [round(t, 4) for t in sorted(plain_ts)],
                prefix + "mtls_window_s":
                    [round(t, 4) for t in sorted(mtls_ts)],
                prefix + "first_check_fresh_conn_ms":
                    round(hs_med, 3),
                prefix + "handshake_ms": round(handshake_ms, 3),
                prefix + "handshake_amortize_1pct_requests": amortize,
                prefix + "method":
                    "paired interleaved windows off one runtime, "
                    "median-of-3; handshake = fresh-connection first "
                    "check minus steady-state per-check",
            }
        finally:
            pool.shutdown(wait=False)
            for cl in clients:
                try:
                    cl.close()
                except Exception:
                    pass
            plain.stop()
            strict.stop()
            srv.close()
    except Exception as exc:
        return {prefix + "error": f"{type(exc).__name__}: {exc}"}


def _soak_bench(on_tpu: bool) -> dict:
    """Whole-mesh chaos soak at sustained scale (istio_tpu/soak/):
    the tier-1 smoke's exact machinery with a longer storm, canary
    gating on, and a bigger fleet — throughput sustained through the
    storm, per-plane p99s over the soak window, the recovery bound,
    and the gate verdicts. Headline fields follow the median-window
    doctrine indirectly: the soak covers the whole storm, so its
    percentiles are storm-inclusive by construction — the honest
    worst-case companion to the clean-path served numbers."""
    prefix = "soak_"
    try:
        from istio_tpu.soak.harness import SoakConfig, run_soak

        cfg = SoakConfig(
            seed=0,
            storm_s=45.0 if on_tpu else 15.0,
            n_rules=64 if on_tpu else 32,
            n_sidecars_grpc=6 if on_tpu else 3,
            n_sidecars_native=2 if on_tpu else 1,
            n_services=24 if on_tpu else 12,
            recovery_timeout_s=60.0,
            canary=True, restart=True)
        res = run_soak(cfg)
        fields: dict = {
            prefix + "seed": res["seed"],
            prefix + "all_gates_ok": res["all_ok"],
            prefix + "gates": {k: bool(v)
                               for k, v in res["gates"].items()},
            prefix + "throughput_rps": res["throughput_rps"],
            prefix + "fleet_checks": res["fleet"]["checks"],
            prefix + "fleet_outcomes": res["fleet"]["outcomes"],
            prefix + "recovery_s":
                res["metrics"]["soak_recovery_s"],
            prefix + "explainability_rate":
                res["metrics"]["soak_explainability_rate"],
            prefix + "violations_after_recovery":
                res["metrics"]["soak_violations_after_recovery"],
            prefix + "fault_kinds":
                res["metrics"]["soak_fault_kinds"],
            prefix + "restart_wall_s": res["restart_wall_s"],
        }
        # per-plane p99s over the soak window (stage histograms
        # deltaed against the storm-start baseline inside run_soak)
        for stage, s in res["latency"].get("stages", {}).items():
            fields[f"{prefix}p99_{stage}_ms"] = s["p99_ms"]
        return fields
    except Exception as exc:
        return {prefix + "error": f"{type(exc).__name__}: {exc}"}


if __name__ == "__main__":
    main()
