"""Config-canary CI gate: seeded divergent swaps MUST be vetoed with
correct per-rule attribution, identical-semantics swaps MUST publish
with zero divergences — on every surface.

Drives istio_tpu/canary over testing/corpus.make_canary_snapshot_pairs
(seeded pairs planting one divergence class each: tightened deny match
→ status flip, denier TTL change → precondition, tightened quota rule
→ quota delta):

  CONTROLLER — a RuntimeServer in --canary=gate serves the seeded
  traffic (the recorder fills at the dispatcher boundary), then the
  store swaps to the DIVERGENT snapshot: the publish must be vetoed
  (old dispatcher object keeps serving, typed CanaryRejected recorded)
  with the planted rule named in the report under the planted
  divergence kind; status-flip exemplars must carry replayable bags
  whose ORACLE RE-EVALUATION (SnapshotOracle over both snapshots)
  confirms the flip. Traffic served after the veto must answer with
  base semantics — zero dropped requests. The IDENTICAL-semantics
  swap (conjuncts reordered, store order reversed) must publish with
  zero reported divergences. Warn mode must publish the divergent
  candidate but record the report.

  INTROSPECT — /debug/canary lists the reports (veto + publish) and
  /metrics carries the mixer_canary_* families.

  CLI — the recorded corpus saves to a file; `canary --config-store
  <divergent dir> --corpus <file>` must exit 1 naming the planted
  rule, and exit 0 against the base dir.

  ADMISSION — kube.admission.register_canary_admission must admit the
  base world in creation order (delta semantics), DENY the divergent
  rule update, and admit the identical rewrite.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_canary_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/canary_smoke.py [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BUCKETS = (16, 32)


def _serve(srv, bags) -> list:
    from istio_tpu.runtime.batcher import pad_to_bucket

    out = []
    for lo in range(0, len(bags), BUCKETS[-1]):
        out.extend(srv.check_batch_preprocessed(pad_to_bucket(
            bags[lo:lo + BUCKETS[-1]], BUCKETS))[
                :len(bags[lo:lo + BUCKETS[-1]])])
    return out


def _swap_store(store, old_docs, new_docs) -> None:
    """Replace the store contents doc-set → doc-set (deleting keys the
    new set no longer carries)."""
    from istio_tpu.runtime.store import Event

    old_keys = {k for k, _ in old_docs}
    new_keys = {k for k, _ in new_docs}
    events = [Event(k, None) for k in old_keys - new_keys]
    events += [Event(k, dict(s)) for k, s in new_docs]
    store.apply_events(events)


def _controller_leg(pair, seed: int, failures: list[str],
                    save_corpus_to: str | None = None) -> None:
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.canary import CanaryRejected, save_corpus
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
    from istio_tpu.testing import corpus

    tag = f"[{pair.kind}]"
    store = MemStore()
    for k, s in pair.base_docs:
        store.set(k, s)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=BUCKETS[-1], buckets=BUCKETS,
        canary="gate", rulestats_drain_s=0,
        default_manifest=corpus.ANALYZER_MANIFEST))
    # the smoke drives rebuilds explicitly — keep the debounce timer
    # from racing a second rebuild mid-assertion
    srv.controller.debounce_s = 60.0
    try:
        bags = [bag_from_mapping(d)
                for d in corpus.make_canary_traffic(pair, seed)]
        recorded = _serve(srv, bags)
        if save_corpus_to:
            save_corpus(save_corpus_to, srv.canary.recorder.corpus())
        d0 = srv.controller.dispatcher

        # -- divergent swap: must veto ---------------------------------
        _swap_store(store, pair.base_docs, pair.divergent_docs)
        d1 = srv.controller.rebuild()
        rej = srv.controller.last_canary_rejection
        if d1 is not d0:
            failures.append(f"{tag} divergent candidate PUBLISHED in "
                            f"gate mode")
            return
        if not isinstance(rej, CanaryRejected):
            failures.append(f"{tag} veto recorded no typed "
                            f"CanaryRejected")
            return
        rep = rej.report
        c = rep.per_rule.get(pair.divergent_rule)
        if c is None:
            failures.append(
                f"{tag} report misattributes: planted rule "
                f"{pair.divergent_rule} absent "
                f"(got {sorted(rep.per_rule)})")
            return
        if not c.get(pair.expected):
            failures.append(f"{tag} planted divergence classified as "
                            f"{c}, expected kind {pair.expected}")
        stray = [r for r in rep.diverging_rules()
                 if r != pair.divergent_rule]
        if pair.kind != "ttl-change" and stray:
            # ttl-change legitimately names every firing deny rule
            # (the shared denier handler's TTL changed for all)
            failures.append(f"{tag} stray diverging rules {stray}")
        if not c["exemplars"]:
            failures.append(f"{tag} no exemplars for the planted rule")
        for ex in c["exemplars"]:
            if not ex.get("bag"):
                failures.append(f"{tag} exemplar carries no "
                                f"replayable bag")
            if ex["kind"] == "status_flip" and \
                    ex.get("oracle_confirmed") is not True:
                failures.append(
                    f"{tag} status-flip exemplar NOT oracle-"
                    f"confirmed: {ex.get('oracle_error', ex.get('oracle_status'))}")

        # -- old dispatcher keeps serving: zero dropped requests -------
        after = _serve(srv, bags)
        for i, (a, b) in enumerate(zip(recorded, after)):
            if a.status_code != b.status_code:
                failures.append(
                    f"{tag} post-veto serving diverged from base at "
                    f"row {i}: {a.status_code} -> {b.status_code}")
                break

        # -- identical-semantics swap: must publish, zero divergences --
        _swap_store(store, pair.divergent_docs, pair.identical_docs)
        d2 = srv.controller.rebuild()
        if d2 is d0:
            failures.append(f"{tag} identical-semantics candidate did "
                            f"not publish")
            return
        last = srv.canary.reports()[-1]
        if last.verdict != "publish" or last.n_divergent:
            failures.append(
                f"{tag} identical-semantics swap reported "
                f"{last.n_divergent}/{last.n_rows} divergences "
                f"(verdict {last.verdict}); diff: "
                f"{json.dumps(last.per_rule, default=str)[:400]}")
    finally:
        srv.close()


def _warn_mode_leg(pair, seed: int, failures: list[str]) -> None:
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
    from istio_tpu.testing import corpus

    store = MemStore()
    for k, s in pair.base_docs:
        store.set(k, s)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=BUCKETS[-1], buckets=BUCKETS,
        canary="warn", rulestats_drain_s=0,
        default_manifest=corpus.ANALYZER_MANIFEST))
    srv.controller.debounce_s = 60.0
    try:
        bags = [bag_from_mapping(d)
                for d in corpus.make_canary_traffic(pair, seed)]
        _serve(srv, bags)
        d0 = srv.controller.dispatcher
        _swap_store(store, pair.base_docs, pair.divergent_docs)
        d1 = srv.controller.rebuild()
        if d1 is d0:
            failures.append("[warn] divergent candidate was VETOED in "
                            "warn mode")
        reports = srv.canary.reports()
        if not reports or reports[-1].verdict != "warn" or \
                pair.divergent_rule not in reports[-1].per_rule:
            failures.append("[warn] warn-mode publish recorded no "
                            "divergence report naming the planted "
                            "rule")
    finally:
        srv.close()


def _introspect_leg(pair, seed: int, failures: list[str]) -> None:
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
    from istio_tpu.testing import corpus
    from istio_tpu.utils import tracing

    store = MemStore()
    for k, s in pair.base_docs:
        store.set(k, s)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=BUCKETS[-1], buckets=BUCKETS,
        canary="gate", rulestats_drain_s=0,
        default_manifest=corpus.ANALYZER_MANIFEST))
    srv.controller.debounce_s = 60.0
    intro = IntrospectServer(runtime=srv)
    try:
        port = intro.start()
        bags = [bag_from_mapping(d)
                for d in corpus.make_canary_traffic(pair, seed)]
        _serve(srv, bags)
        _swap_store(store, pair.base_docs, pair.divergent_docs)
        srv.controller.rebuild()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/canary?shadow=0",
                timeout=30) as r:
            view = json.loads(r.read().decode())
        if view.get("mode") != "gate" or not view.get("reports"):
            failures.append(f"[introspect] /debug/canary empty: "
                            f"{str(view)[:200]}")
        else:
            last = view["reports"][-1]
            if last.get("verdict") != "veto" or \
                    pair.divergent_rule not in last.get("per_rule", {}):
                failures.append("[introspect] /debug/canary last "
                                "report is not the veto naming the "
                                "planted rule")
            if "last_rejection" not in view:
                failures.append("[introspect] /debug/canary carries "
                                "no last_rejection")
        if not view.get("recorder", {}).get("entries"):
            failures.append("[introspect] recorder stats empty")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            mtext = r.read().decode()
        for fam in ("mixer_canary_replays_total",
                    "mixer_canary_divergences_total",
                    "mixer_canary_verdicts_total",
                    "mixer_canary_last_divergence_rate"):
            if fam not in mtext:
                failures.append(f"[introspect] metric family absent "
                                f"from /metrics: {fam}")
    finally:
        intro.close()
        srv.close()
        tracing.shutdown()


def _docs_to_fsstore(tmp: str, name: str, docs) -> str:
    """Write [(key, spec)] docs as an FsStore YAML directory."""
    import yaml

    root = os.path.join(tmp, name)
    os.makedirs(root, exist_ok=True)
    payload = [{"kind": kind,
                "metadata": {"name": n, "namespace": ns},
                "spec": spec}
               for (kind, ns, n), spec in docs]
    with open(os.path.join(root, "world.yaml"), "w",
              encoding="utf-8") as f:
        yaml.safe_dump_all(payload, f, sort_keys=False)
    return root


def _cli_leg(pair, corpus_path: str, failures: list[str]) -> None:
    import contextlib
    import io

    from istio_tpu.cmd.__main__ import main as cli_main

    def run(argv) -> tuple[int, str]:
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(argv)
        return rc, buf.getvalue()

    with tempfile.TemporaryDirectory() as tmp:
        base = _docs_to_fsstore(tmp, "base", pair.base_docs)
        div = _docs_to_fsstore(tmp, "divergent", pair.divergent_docs)
        rc, out = run(["canary", "--config-store", base,
                       "--corpus", corpus_path])
        if rc != 0:
            failures.append(f"[cli] exit {rc} against the BASE store "
                            f"(expected 0): {out[:200]}")
        rc, out = run(["canary", "--config-store", div,
                       "--corpus", corpus_path, "--json"])
        if rc != 1:
            failures.append(f"[cli] exit {rc} against the divergent "
                            f"store (expected 1)")
        else:
            rep = json.loads(out)
            if pair.divergent_rule not in rep.get("per_rule", {}):
                failures.append(f"[cli] report misses the planted "
                                f"rule {pair.divergent_rule}")
        # waiving the planted rule must flip the verdict back to 0
        rc, _out = run(["canary", "--config-store", div,
                        "--corpus", corpus_path,
                        "--waive", pair.divergent_rule])
        if rc != 0:
            failures.append(f"[cli] exit {rc} with the planted rule "
                            f"waived (expected 0)")


def _admission_leg(pair, corpus_path: str, failures: list[str]) -> None:
    from istio_tpu.canary import load_corpus
    from istio_tpu.kube.admission import register_canary_admission
    from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster
    from istio_tpu.testing import corpus as tcorpus

    entries = load_corpus(corpus_path)
    cluster = FakeKubeCluster()
    register_canary_admission(
        cluster, corpus_fn=lambda: entries,
        default_manifest=tcorpus.ANALYZER_MANIFEST, buckets=BUCKETS)

    def obj(key, spec):
        kind, ns, name = key
        return {"kind": kind,
                "metadata": {"name": name, "namespace": ns},
                "spec": spec}

    try:
        for key, spec in pair.base_docs:
            cluster.create(obj(key, spec))
    except AdmissionDenied as exc:
        failures.append(f"[admission] base world rejected in creation "
                        f"order (delta semantics broken): {exc}")
        return
    base_by_key = {k: s for k, s in pair.base_docs}
    changed = [(k, s) for k, s in pair.divergent_docs
               if base_by_key.get(k) != s]
    if not changed:
        failures.append(f"[admission] {pair.kind} pair has no changed "
                        f"doc")
        return
    for key, spec in changed:
        try:
            cluster.update(obj(key, spec))
            failures.append(f"[admission] divergent {key} ADMITTED")
        except AdmissionDenied:
            pass
    # identical rewrite of an existing rule must stay admitted
    ident_by_key = {k: s for k, s in pair.identical_docs}
    rule_keys = [k for k in ident_by_key
                 if k[0] == "rule" and k in base_by_key]
    if not rule_keys:
        failures.append(f"[admission] {pair.kind}: no rule doc to "
                        f"test the identical rewrite with")
    for key in rule_keys[:2]:
        try:
            cluster.update(obj(key, ident_by_key[key]))
        except AdmissionDenied as exc:
            failures.append(f"[admission] identical rewrite of {key} "
                            f"rejected: {exc}")


def main(seed: int = 20260803) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.testing import corpus

    failures: list[str] = []
    pairs = corpus.make_canary_snapshot_pairs(seed)
    corpus_paths: dict[int, str] = {}
    with tempfile.TemporaryDirectory() as tmp:
        for i, pair in enumerate(pairs):
            # pairs 0 (rule-doc divergence) and 1 (handler-doc
            # divergence) feed the CLI/admission legs too
            save_to = os.path.join(tmp, f"corpus{i}.json") if i < 2 \
                else None
            _controller_leg(pair, seed, failures,
                            save_corpus_to=save_to)
            if save_to and os.path.exists(save_to):
                corpus_paths[i] = save_to
        _warn_mode_leg(pairs[0], seed, failures)
        _introspect_leg(pairs[0], seed, failures)
        if 0 in corpus_paths:
            _cli_leg(pairs[0], corpus_paths[0], failures)
        else:
            failures.append("no corpus file was saved for the CLI leg")
        for i in sorted(corpus_paths):
            # i=1 is the ttl-change pair: its divergent doc is a
            # HANDLER update — the admission hook's default kinds
            # must cover it, not just rule docs
            _admission_leg(pairs[i], corpus_paths[i], failures)
    for f in failures:
        print(f"canary_smoke: FAIL: {f}")
    if not failures:
        print(f"canary_smoke: ok (seed={seed}: {len(pairs)} seeded "
              f"divergence classes vetoed in gate mode with per-rule "
              f"attribution + oracle-confirmed flips; identical-"
              f"semantics swaps published with zero divergences; "
              f"warn/introspect/CLI/admission surfaces agree)")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=20260803,
                    help="reproducible corpus seed")
    sys.exit(main(seed=ap.parse_args().seed))
