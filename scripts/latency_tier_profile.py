"""Ablation profile of the B=64 latency-tier step at 10k rules: which
component carries the fixed rule-axis cost that keeps the tier above
the 1ms budget? (VERDICT r4 item 2). Runs on the real device; median
of 3 deep chained windows per variant."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    import numpy as np

    import bench  # noqa: F401 (jax cache config)
    from istio_tpu.testing import workloads

    B = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    engine = workloads.make_engine(n_rules=10_000, with_quota=True,
                                   jit=False)
    bags = workloads.make_bags(B)
    ab = jax.device_put(engine.tensorizer.tensorize(bags))
    req_ns = jax.device_put(np.asarray(
        workloads.make_request_ns(engine, B)))
    params = jax.device_put(engine.params)
    counts = engine.quota_counts
    sync = bench._roundtrip_s()
    print(f"B={B} sync {sync*1e3:.1f} ms")

    def timed(label, fn, n=200):
        out = fn()
        jax.block_until_ready(out)
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn()
            jax.block_until_ready(out)
            ts.append((time.perf_counter() - t0 - sync) / n)
        ts.sort()
        print(f"{label:34s} med {ts[1]*1e3:7.3f}  "
              f"min {ts[0]*1e3:7.3f}  max {ts[2]*1e3:7.3f} ms")
        return ts[1]

    step = jax.jit(engine.raw_step)
    timed("full engine step",
          lambda: step(params, ab, req_ns, counts)[0].status)

    rs_fn = jax.jit(engine.ruleset.fn)
    timed("ruleset match only",
          lambda: rs_fn(params, ab)[0])

    # match + namespace mask + deny combine, nothing else
    rule_ns = jnp.asarray(engine.ruleset.rule_ns)
    default_ns = engine.ruleset.ns_ids[""]

    @jax.jit
    def match_deny(params, batch, req_ns):
        matched, _, err = engine.ruleset.fn(params, batch)
        ns_ok = (rule_ns[None, :] == default_ns) | \
                (rule_ns[None, :] == req_ns[:, None])
        active = matched & ns_ok
        BIGI = jnp.iinfo(jnp.int32).max
        rule_idx = jnp.arange(active.shape[1], dtype=jnp.int32)
        d_key = jnp.where(active, rule_idx[None, :], BIGI)
        return jnp.min(d_key, axis=1)
    timed("match+ns+argmin", lambda: match_deny(params, ab, req_ns))

    # referenced bitmap dot alone
    attr_mask = jnp.asarray(engine.ruleset.attr_mask.astype(np.int8))
    ns_ok_c = jax.device_put(np.ones((B, attr_mask.shape[0]), np.int8))
    dims = (((1,), (0,)), ((), ()))

    @jax.jit
    def ref_dot(ns_ok):
        return jax.lax.dot_general(ns_ok, attr_mask, dims,
                                   preferred_element_type=jnp.int32) > 0
    timed("referenced dot [B,R]@[R,W]", lambda: ref_dot(ns_ok_c))

    # quota rank sort alone (Q buckets x B)
    from istio_tpu.models.policy_engine import _batch_rank
    nq = counts.shape[0]
    ckey = jax.device_put(
        np.random.default_rng(0).integers(
            0, 1 << 20, (B, nq)).astype(np.int32))

    @jax.jit
    def rank_only(ck):
        return _batch_rank(ck.T.reshape(-1)).reshape(nq, B).T
    timed(f"quota rank sort (Q={nq})", lambda: rank_only(ckey))

    # ruleset internals: atom eval vs rule fold — report param sizes
    tot = 0
    for leaf in jax.tree.leaves(params):
        tot += leaf.size * leaf.dtype.itemsize
    print(f"ruleset param bytes: {tot/1e6:.1f} MB")
