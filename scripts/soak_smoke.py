"""Tier-1 gate for the whole-mesh chaos soak (istio_tpu/soak/) — the
CI proof that the mesh survives a seeded storm and recovers to
all-gates-green.

A FleetSimulator runs simulated sidecars through BOTH real fronts
(gRPC + native) with client check-caches, quota traffic and the xDS
watch loop, while a seeded StormChoreographer replays control-side
chaos against the live server: adapter wedge + latency, a device-fault
burst into oracle fallback with a quota-backend stall armed inside the
outage, a delayed discovery publish, namespace churn, mixer config
swaps (grant revocation storms), and a mid-soak quiesce→restart under
live traffic. FAILS (nonzero exit) unless every recovery gate passes:
exact report conservation across the restart, audit all-ok within the
bound, fault-explainability rate 1.0 with >= 3 distinct injected
kinds matched, zero stale-generation grants, discovery↔mixer plane
agreement, and the client-ledger ↔ mixer_* accounting identity.

The storm schedule is pure f(seed): a failure replays exactly from
the printed seed line. Runnable under JAX_PLATFORMS=cpu; tier-1
invokes main() in-process (tests/test_soak_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/soak_smoke.py [--seed N]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(seed: int = 0, storm_s: float = 6.0,
         result_sink: dict | None = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.runtime.audit import INJECTIONS, SEAMS
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.soak.harness import SoakConfig, run_soak
    from istio_tpu.utils import tracing

    print(f"soak seed: {seed} (replay: JAX_PLATFORMS=cpu "
          f"python scripts/soak_smoke.py --seed {seed})")
    failures: list[str] = []
    out = None
    try:
        out = run_soak(SoakConfig(seed=seed, storm_s=storm_s))
        if result_sink is not None:
            result_sink.update(out)
        for name, ok in out["gates"].items():
            if not ok:
                failures.append(f"gate {name} failed: "
                                f"{out['detail'].get(name, '')}")
        m = out["metrics"]
        kinds = m["soak_fault_kinds"]
        if len(kinds) < 3:
            failures.append(f"fewer than 3 fault kinds explained: "
                            f"{kinds}")
        if out["restarts"] != 1:
            failures.append(f"expected exactly one mid-soak restart, "
                            f"got {out['restarts']}")
        if failures and out is not None:
            print("soak detail:", out["detail"])
    except Exception as exc:     # noqa: BLE001 — smoke must report
        failures.append(f"soak raised: {type(exc).__name__}: {exc}")
        import traceback
        traceback.print_exc()
    finally:
        SEAMS.reset()
        INJECTIONS.reset()
        CHAOS.reset()
        tracing.shutdown()

    if failures:
        print("soak smoke FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    m = out["metrics"]
    print(f"soak smoke ok: fleet {out['fleet']['checks']} checks "
          f"({out['throughput_rps']} rps) through a seeded storm "
          f"(kinds: {','.join(m['soak_fault_kinds'])}), "
          f"restart survived with exact conservation, recovered in "
          f"{m['soak_recovery_s']}s, explainability "
          f"{m['soak_explainability_rate']}, violations after "
          f"recovery {m['soak_violations_after_recovery']}")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--storm-s", type=float, default=6.0)
    a = ap.parse_args()
    raise SystemExit(main(seed=a.seed, storm_s=a.storm_s))
