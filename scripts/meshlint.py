"""meshlint CI gate — proves the analyzer catches AND the tree is
clean, in one tier-1-runnable script.

Three legs, all must hold (exit 1 otherwise):

  1. **Seeded corpus** (fixtures.selftest): every violation class —
     lock-order cycle/inversion/leaf/self-deadlock, hot-path
     host-sync, missing hot root, unregistered / non-zero-shaped /
     mislabeled metric, untyped front escape — is flagged with a
     file:line witness, pragmas are honored, and the clean fixture
     stays silent. A gate that cannot demonstrate detection is
     indistinguishable from a broken one.
  2. **Clean tree**: the real repo yields ZERO ERROR-severity
     findings (real violations get fixed or pragma'd with a reason
     in the same PR that introduces them).
  3. **Superset pin**: the inferred hot-path coverage contains every
     (file, function) the retired hand-maintained HOT_SECTIONS list
     named — a call-graph regression that silently drops a once-hot
     function fails here, not in production.

Usage: python scripts/meshlint.py [--root DIR]
(tier-1 runs main() via tests/test_meshlint_smoke.py)
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(root: str | None = None) -> int:
    root = root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    from istio_tpu.analysis.meshlint import fixtures, run_meshlint

    failures: list[str] = []

    # -- leg 1: seeded violation corpus -------------------------------
    problems = fixtures.selftest()
    for p in problems:
        failures.append(f"selftest: {p}")
    print(f"meshlint gate: selftest "
          f"{'ok' if not problems else 'FAILED'} "
          f"({len(fixtures.FIXTURES)} fixtures, "
          f"{len(problems)} problem(s))")

    # -- leg 2: the real tree is ERROR-silent -------------------------
    report = run_meshlint(root=root)
    for f in report.errors:
        failures.append(f"tree: {f}")
    print(f"meshlint gate: tree "
          f"{'ok' if not report.errors else 'FAILED'} "
          f"({report.n_functions} functions in {report.n_modules} "
          f"modules, {len(report.errors)} error(s), "
          f"{len(report.warnings)} warning(s), "
          f"{report.wall_ms:.0f}ms)")

    # -- leg 3: inferred coverage ⊇ the retired HOT_SECTIONS list -----
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "hotpath_lint", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "hotpath_lint.py"))
    shim = importlib.util.module_from_spec(spec)
    sys.modules.setdefault(spec.name, shim)
    spec.loader.exec_module(shim)
    coverage = report.stats.get("hot_coverage", {})
    dropped = [
        f"{path}::{name}"
        for path, names in sorted(shim.HOT_SECTIONS.items())
        for name in sorted(names)
        if name not in set(coverage.get(path, ()))]
    for d in dropped:
        failures.append(f"superset: {d} was hot under HOT_SECTIONS "
                        f"but is not inferred-reachable")
    baseline = sum(len(v) for v in shim.HOT_SECTIONS.values())
    print(f"meshlint gate: superset "
          f"{'ok' if not dropped else 'FAILED'} "
          f"(inferred {report.stats.get('hot_reachable', 0)} ⊇ "
          f"baseline {baseline}, {len(dropped)} dropped)")

    if failures:
        print(f"meshlint gate: {len(failures)} failure(s)")
        for f in failures:
            print(f"  FAIL {f}")
        return 1
    print("meshlint gate: all legs green")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=None)
    sys.exit(main(root=ap.parse_args().root))
