"""Tier-1 gate for the mesh audit plane (istio_tpu/runtime/audit.py)
— the CI proof that the background invariant auditor actually audits.
Boots a RuntimeServer with the audit thread on, serves REAL traffic
over the gRPC front AND the native C++ front, and FAILS (nonzero
exit) unless:

  1. CLEAN LOAD IS SILENT: after the traffic drains, every one of the
     six invariants reads ok, the violation counters never moved, and
     the fault-explainability rate is vacuously 1.0 (no injections,
     nothing unexplained). /debug/audit and /debug/slo serve the same
     verdicts over real HTTP.
  2. EVERY FAULT CLASS IS EXPLAINABLE: a chaos-wedged adapter and an
     injected device-step fault both register expected-signature
     records, and the auditor matches each to forensics evidence by
     name (breaker event / host-lane exemplar / typed counter delta)
     — explainability rate 1.0, zero expired-unmatched.
  3. CORRUPTION IS CAUGHT: a deliberately skewed conservation counter
     (the AuditSeams test-only seam — production counters are never
     writable) flips report_conservation to violated within the
     stuck-detection window, drops mixer_audit_healthy to 0, emits an
     audit_violation forensics event, and /debug/audit carries the
     ledger evidence. Clearing the seam recovers to healthy.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_audit_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/audit_smoke.py [--rules N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WEDGED = "cilist.istio-system"
DEADLINE_MS = 600.0


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.load(r)


def _overlay_request(i: int, n_services: int) -> dict:
    """Request matching make_store(host_overlay_every=5) rule i (the
    executor_smoke convention — i % 5 == 2, k == 0 → cilist)."""
    return {
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        "request.path": f"/api/v{i % 3}/items",
    }


def _check(snap: dict, name: str) -> dict:
    return next(c for c in snap["checks"] if c["name"] == name)


def main(n_rules: int = 40, n_checks: int = 24,
         seed: int | None = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.api.native_server import NativeMixerServer
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import monitor
    from istio_tpu.runtime.audit import INJECTIONS, SEAMS
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    CHAOS.reset()
    INJECTIONS.reset()
    SEAMS.reset()
    if seed is not None:
        # same seed/replay contract as chaos_smoke and soak_smoke:
        # the printed line reproduces the failing corpus exactly
        CHAOS.seed = seed
        print(f"audit seed: {seed} (replay: JAX_PLATFORMS=cpu "
              f"python scripts/audit_smoke.py --seed {seed})")
    n_services = max(n_rules // 2, 1)
    store = workloads.make_store(n_rules, host_overlay_every=5,
                                 seed=seed)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        default_check_deadline_ms=DEADLINE_MS,
        host_breaker_failures=2, host_breaker_reset_s=0.4,
        audit_interval_s=0.2,
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    g = MixerGrpcServer(runtime=srv)
    native = NativeMixerServer(srv, min_fill=8, window_us=500)
    gclient = nclient = None
    try:
        if srv.audit is None:
            failures.append("audit plane not created despite "
                            "audit=True (the default)")
            raise RuntimeError("no auditor")
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 16))
        http_port = intro.start()
        gclient = MixerClient(f"127.0.0.1:{g.start()}",
                              enable_check_cache=False)
        nclient = MixerClient(f"127.0.0.1:{native.start()}",
                              enable_check_cache=False)

        # ---- 1. clean traffic over both fronts: silence ------------
        base_counters = monitor.audit_counters()
        reqs = workloads.make_request_dicts(
            n_checks, seed=1 if seed is None else seed)
        for i, rq in enumerate(reqs):
            (gclient if i % 2 else nclient).check(rq)
        gclient.report(reqs[: n_checks // 2])
        cons_deadline = time.time() + 20
        while time.time() < cons_deadline and \
                monitor.report_conservation()["in_flight"]:
            time.sleep(0.02)

        snap = srv.audit.evaluate()
        bad = [c["name"] for c in snap["checks"]
               if c["status"] != "ok"]
        if bad:
            failures.append(f"clean load left non-ok invariants: "
                            f"{bad}")
        cnt = monitor.audit_counters()
        moved = {inv: cnt["violations"][inv]
                 - base_counters["violations"][inv]
                 for inv in cnt["violations"]
                 if cnt["violations"][inv]
                 != base_counters["violations"][inv]}
        if moved:
            failures.append(f"violation counters moved under clean "
                            f"load: {moved}")
        ex = snap["explainability"]
        if ex["rate"] != 1.0 or ex["matched"] or ex["unexplained"]:
            failures.append(f"explainability not vacuous under clean "
                            f"load: {ex}")
        if not snap["healthy"]:
            failures.append("audit_healthy low with zero violations")

        # the same verdicts over real HTTP
        via_http = _get_json(http_port, "/debug/audit")
        if not via_http.get("healthy", False):
            failures.append("/debug/audit disagrees: healthy false")
        if [c["status"] for c in via_http.get("checks", ())] \
                != ["ok"] * 6:
            failures.append(f"/debug/audit not all-ok: "
                            f"{via_http.get('checks')}")
        slo = _get_json(http_port, "/debug/slo")
        if set(slo.get("planes", {})) != {"check_wire",
                                          "report_export",
                                          "discovery_push",
                                          "quota_flush", "audit"}:
            failures.append(f"/debug/slo plane set wrong: "
                            f"{sorted(slo.get('planes', {}))}")
        if slo["planes"]["audit"]["verdict"] != "ok":
            failures.append(f"/debug/slo audit verdict not ok: "
                            f"{slo['planes']['audit']}")

        # ---- 2. every chaos fault class is explainable -------------
        ci_rules = [i for i in range(2, n_rules, 5)
                    if (i // 5) % 3 == 0]
        if not ci_rules:
            failures.append("overlay workload lost its cilist rules")
            raise RuntimeError("bad workload")
        CHAOS.wedge_adapter(WEDGED)
        for k in range(6):
            gclient.check(_overlay_request(
                ci_rules[k % len(ci_rules)], n_services))
        CHAOS.unwedge_adapter(WEDGED)
        CHAOS.device_failures = 3
        for rq in reqs[:6]:
            gclient.check(rq)
        CHAOS.reset()

        time.sleep(0.3)     # let the typed outcomes land
        snap = srv.audit.evaluate()
        ex = snap["explainability"]
        per_kind = {r["kind"]: r for r in ex["records"]
                    if r["matched"]}
        if "wedge" not in per_kind:
            failures.append(f"wedged adapter not explained: "
                            f"{ex['records']}")
        elif not per_kind["wedge"]["matched_by"]:
            failures.append("wedge matched without naming evidence")
        if "device" not in per_kind:
            failures.append(f"device fault not explained: "
                            f"{ex['records']}")
        elif not per_kind["device"]["matched_by"]:
            failures.append("device matched without naming evidence")
        if ex["unexplained"] or ex["rate"] != 1.0:
            failures.append(f"explainability rate under chaos not "
                            f"1.0: {ex}")
        print(f"audit smoke: chaos explained — "
              + ", ".join(f"{k}<-{v['matched_by']}"
                          for k, v in sorted(per_kind.items())))

        # ---- 3. a corrupted counter flips audit_healthy ------------
        # the test-only seam skews the accepted reading; the ledger
        # residue is frozen (no traffic), so the stuck detector
        # promotes degraded -> violated
        SEAMS.report_accepted_skew = 7
        # stuck promotion needs the residue frozen past BOTH the
        # evaluation count and the time floor (stuck_floor_s covers
        # the serving deadline) — poll until the detector fires
        catch_deadline = time.time() + srv.audit.stuck_floor_s + 10
        rc = _check(srv.audit.evaluate(), "report_conservation")
        while rc["status"] != "violated" and \
                time.time() < catch_deadline:
            time.sleep(0.2)
            rc = _check(srv.audit.evaluate(), "report_conservation")
        snap = srv.audit.snapshot()
        if rc["status"] != "violated":
            failures.append(f"skewed counter not caught: {rc}")
        if snap["healthy"]:
            failures.append("audit_healthy still high under a "
                            "violated invariant")
        via_http = _get_json(http_port, "/debug/audit")
        ev = _check(via_http, "report_conservation")\
            .get("evidence", {})
        if ev.get("in_flight") != 7:
            failures.append(f"/debug/audit evidence missing the "
                            f"skewed residue: {ev}")
        events = _get_json(
            http_port, "/debug/events?type=audit_violation")
        if not any(e.get("detail", {}).get("invariant")
                   == "report_conservation"
                   for e in events.get("events", ())):
            failures.append("no audit_violation event for the "
                            "skewed invariant")
        SEAMS.reset()
        snap = srv.audit.evaluate()
        if _check(snap, "report_conservation")["status"] != "ok" \
                or not snap["healthy"]:
            failures.append(f"auditor did not recover after the seam "
                            f"cleared: {snap['healthy']}")
    finally:
        SEAMS.reset()
        INJECTIONS.reset()
        CHAOS.reset()
        for c in (gclient, nclient):
            if c is not None:
                c.close()
        native.stop()
        g.stop()
        intro.close()
        srv.close()
        tracing.shutdown()

    if failures:
        print("audit smoke FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print("audit smoke ok: six invariants silent under clean "
          "two-front load, every chaos fault class explained "
          "(rate 1.0), corrupted counter flips audit_healthy with "
          "evidence served")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=40)
    ap.add_argument("--checks", type=int, default=24)
    ap.add_argument("--seed", type=int, default=None,
                    help="reproducible corpus seed (rules + bags)")
    a = ap.parse_args()
    raise SystemExit(main(n_rules=a.rules, n_checks=a.checks,
                          seed=a.seed))
