"""Chaos smoke: the CI gate that overload resilience actually works.

Boots a small serving stack and drives the three failure modes the
resilience ISSUE pins, failing (nonzero exit) unless each degrades the
way the design says it must:

  (a) DEVICE OUTAGE — injected device-step failures (ChaosHooks) trip
      the circuit breaker and Check() keeps answering CORRECTLY via
      the CPU oracle fallback: conformance parity is asserted against
      the clean-path statuses on a corpus sample that includes denials,
      and the half-open probe recovers the breaker once the fault
      clears.
  (b) QUEUE SATURATION — with a slow device (injected latency) and a
      small queue cap, excess submits shed RESOURCE_EXHAUSTED instead
      of growing queue_wait without bound; everything admitted still
      resolves.
  (c) EXPIRED DEADLINES — requests whose deadline already passed are
      rejected DEADLINE_EXCEEDED before tensorize (the tensorize stage
      count must not move).

Breaker state and the shed/expired/fallback counters must be visible
over real HTTP in /metrics AND /debug/resilience. Runnable under
JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_chaos_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [--rules N]
"""
import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_METRICS = ("mixer_check_shed_total",
                    "mixer_check_deadline_expired_total",
                    "mixer_check_fallback_total",
                    "mixer_check_batch_failures_total",
                    "mixer_check_breaker_state")


def _deny_bags(n: int = 4) -> list:
    """Bags that deterministically hit deny rules of the
    workloads.make_store ruleset (every 3rd rule denies), so the
    conformance sample carries non-OK statuses — parity over an all-OK
    sample would prove nothing about the fallback's verdict logic."""
    from istio_tpu.attribute.bag import bag_from_mapping
    return [bag_from_mapping({
        "destination.service": f"svc{3 * i}.ns{(3 * i) % 23}"
                               ".svc.cluster.local",
        "source.namespace": "ns1",
        "request.method": "GET",
    }) for i in range(n)]


def main(n_rules: int = 24, n_checks: int = 40,
         seed: int | None = None) -> int:
    """`seed` threads end-to-end into the workload generators
    (rule constants + request bags) so a chaos corpus replays
    identically across CI runs; None = the legacy fixed corpus."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import monitor
    from istio_tpu.runtime.resilience import (CHAOS,
                                              DeadlineExceededError,
                                              ResourceExhaustedError)
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    CHAOS.reset()
    if seed is not None:
        # replay provenance: the seed rides the chaos seam snapshot so
        # a failure's artifacts name the exact corpus that produced it
        CHAOS.seed = seed
        print(f"chaos seed: {seed} (replay: JAX_PLATFORMS=cpu "
              f"python scripts/chaos_smoke.py --seed {seed})")
    store = workloads.make_store(n_rules, seed=seed)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        check_queue_cap=32, breaker_failures=2, breaker_reset_s=0.3,
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 16))
        port = intro.start()
        bags = workloads.make_bags(
            n_checks, seed=1 if seed is None else seed) \
            + _deny_bags()

        # clean-path statuses = the conformance baseline
        clean = [srv.check(b).status_code for b in bags]
        if not any(clean):
            failures.append("corpus sample carries no denials — the "
                            "parity assertion would be vacuous")

        # (a) device outage → breaker trips → oracle fallback parity
        CHAOS.device_failures = 10**9
        degraded = [srv.check(b).status_code for b in bags]
        if degraded != clean:
            failures.append(
                f"oracle fallback lost conformance parity: "
                f"{sum(a != b for a, b in zip(degraded, clean))}/"
                f"{len(clean)} statuses changed")
        if srv.resilience.breaker.state != "open":
            failures.append(
                f"breaker did not trip under device outage "
                f"(state={srv.resilience.breaker.state})")
        c = monitor.resilience_counters()
        if c["fallback_total"] < len(bags):
            failures.append(
                f"fallback counter undercounts: {c['fallback_total']} "
                f"< {len(bags)}")
        # fault clears → half-open probe recovers the breaker
        CHAOS.reset()
        time.sleep(0.35)
        if srv.check(bags[0]).status_code != clean[0]:
            failures.append("post-recovery answer diverged")
        if srv.resilience.breaker.state != "closed":
            failures.append(
                f"breaker did not recover via half-open probe "
                f"(state={srv.resilience.breaker.state})")

        # (b) queue saturation → RESOURCE_EXHAUSTED sheds, bounded depth
        CHAOS.device_latency_s = 0.05
        shed0 = monitor.resilience_counters()["shed"]["queue_full"]
        futs = [srv.batcher.submit(bags[i % len(bags)])
                for i in range(200)]
        depth = srv.batcher.stats()["depth"]
        if depth > 32:
            failures.append(f"queue depth {depth} exceeded its 32 cap")
        n_shed = n_ok = 0
        for f in futs:
            try:
                f.result(timeout=30)
                n_ok += 1
            except ResourceExhaustedError:
                n_shed += 1
            except Exception as exc:
                failures.append(f"unexpected submit outcome: "
                                f"{type(exc).__name__}: {exc}")
                break
        CHAOS.reset()
        if n_shed == 0:
            failures.append("saturation shed nothing "
                            f"(ok={n_ok} of {len(futs)})")
        c = monitor.resilience_counters()
        if c["shed"]["queue_full"] - shed0 != n_shed:
            failures.append(
                f"shed counter mismatch: counter moved "
                f"{c['shed']['queue_full'] - shed0}, clients saw "
                f"{n_shed}")

        # (c) expired deadline → rejected pre-tensorize
        tz0 = monitor.CHECK_STAGE_SECONDS.count(stage="tensorize")
        exp0 = monitor.resilience_counters()["expired_total"]
        for b in bags[:5]:
            try:
                srv.check(b, deadline=time.perf_counter() - 1.0)
                failures.append("expired-deadline check was answered")
            except DeadlineExceededError:
                pass
        c = monitor.resilience_counters()
        if c["expired_total"] - exp0 != 5:
            failures.append(
                f"expired counter moved {c['expired_total'] - exp0}, "
                "expected 5")
        if monitor.CHECK_STAGE_SECONDS.count(stage="tensorize") != tz0:
            failures.append("expired requests were tensorized")

        # counters + breaker visible over real HTTP
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        for name in REQUIRED_METRICS:
            if name not in text:
                failures.append(f"metric absent from /metrics: {name}")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/resilience",
                timeout=10) as r:
            dbg = json.load(r)
        for key in ("counters", "breaker", "policy", "batcher"):
            if key not in dbg:
                failures.append(f"/debug/resilience missing {key!r}")
        if dbg.get("counters", {}).get("shed_total", 0) < n_shed:
            failures.append("/debug/resilience shed_total below the "
                            "observed sheds")
    finally:
        CHAOS.reset()
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"chaos smoke ok: breaker tripped+recovered, "
              f"oracle parity held on {n_checks + 4} checks, "
              f"saturation shed RESOURCE_EXHAUSTED, expired deadlines "
              f"rejected pre-tensorize")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=24)
    ap.add_argument("--checks", type=int, default=40)
    ap.add_argument("--seed", type=int, default=None,
                    help="reproducible corpus seed (rules + bags)")
    args = ap.parse_args()
    sys.exit(main(args.rules, args.checks, seed=args.seed))
