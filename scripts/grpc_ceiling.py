"""Loopback grpc ceiling: an echo aio server + the perf worker client,
one core — the structural upper bound for any served number on this
box, independent of policy-engine work.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    import asyncio
    import threading

    import grpc
    from grpc import aio

    from istio_tpu.testing import perf, workloads

    payloads = perf.make_check_payloads(
        workloads.make_request_dicts(128))
    resp = b"\x0a\x02\x08\x00"   # tiny canned bytes

    ready = threading.Event()
    port_box = [0]

    def run_server():
        async def echo(request, context):
            return resp

        async def serve():
            server = aio.server()
            handlers = {
                "Check": grpc.unary_unary_rpc_method_handler(
                    echo,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b),
            }
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "istio.mixer.v1.Mixer", handlers),))
            port_box[0] = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            ready.set()
            await server.wait_for_termination()

        asyncio.run(serve())

    t = threading.Thread(target=run_server, daemon=True)
    t.start()
    ready.wait(10)

    for conc in (256, 2048):
        t0 = time.time()
        rep = perf.run_load(f"127.0.0.1:{port_box[0]}", payloads,
                            n_record=8000, n_procs=1, concurrency=conc,
                            warmup_s=1.0)
        print(f"conc={conc}: {rep.checks_per_sec:.0f}/s "
              f"p50={rep.p50_ms:.1f}ms p99={rep.p99_ms:.1f}ms "
              f"err={rep.n_errors} wall={time.time() - t0:.0f}s")
