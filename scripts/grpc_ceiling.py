"""Loopback grpc ceiling: an echo aio server + the perf worker client,
one core — the structural upper bound for any served number on this
box, independent of policy-engine work.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    from istio_tpu.testing import perf, workloads
    from istio_tpu.testing.echo import start_echo_server

    port, stop = start_echo_server()
    payloads = perf.make_check_payloads(
        workloads.make_request_dicts(128))
    try:
        for conc in (256, 2048):
            t0 = time.time()
            rep = perf.run_load(f"127.0.0.1:{port}", payloads,
                                n_record=8000, n_procs=1,
                                concurrency=conc, warmup_s=1.0)
            print(f"conc={conc}: {rep.checks_per_sec:.0f}/s "
                  f"p50={rep.p50_ms:.1f}ms p99={rep.p99_ms:.1f}ms "
                  f"err={rep.n_errors} wall={time.time() - t0:.0f}s")
    finally:
        stop()
