"""Hot-path lint — THIN SHIM over istio_tpu/analysis/meshlint.

The detection logic (host-sync/blocking/allocation checks, the
`# hotpath: sync-ok` pragma grammar) and, more importantly, the
COVERAGE now live in `istio_tpu.analysis.meshlint.hotpath`: instead
of this file's hand-maintained HOT_SECTIONS list, the analyzer
computes reachability from the hot entry points, so a new helper
called from hot code is covered the moment it is called — with no
list to extend per PR.

What stays here:

  * `HOT_SECTIONS` — FROZEN as the historical baseline. It is no
    longer the coverage source; it is the floor the superset test
    (tests/test_meshlint_smoke.py) pins the inferred coverage
    against, so a call-graph regression that silently drops a
    once-hot function fails loudly. Do NOT extend it for new code —
    new hot helpers are inferred.
  * `lint_source` / `Violation` — the single-module lint surface
    tests and downstream tooling import; delegates to the meshlint
    detector.
  * `main()` — runs the meshlint hot-path pass over the repo
    (tier-1 calls this via tests/test_hotpath_lint.py).

Usage: python scripts/hotpath_lint.py [--root DIR]   (exit 1 on
violations)
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PRAGMA = "hotpath: sync-ok"

# FROZEN baseline (see module docstring): the last hand-maintained
# coverage list, kept as the superset-pin floor for the inferred
# reachability in istio_tpu/analysis/meshlint/hotpath.py.
HOT_SECTIONS: dict[str, frozenset[str]] = {
    "istio_tpu/runtime/batcher.py": frozenset({
        "CheckBatcher.submit", "CheckBatcher._loop",
        "CheckBatcher._flush", "CheckBatcher._shed_stale",
        "CheckBatcher._run_one", "CheckBatcher._min_deadline",
        "CheckBatcher._drain_on_close",
    }),
    "istio_tpu/runtime/dispatcher.py": frozenset({
        "Dispatcher.check", "Dispatcher._check_fused",
        "Dispatcher._resolve", "Dispatcher._overlay_fallback",
        "Dispatcher._overlay_active",
        "Dispatcher._tensorize_for_device",
        "Dispatcher._ns_ids_from_batch",
        "Dispatcher._request_ns_ids",
        "Dispatcher._report_active_fused",
        "Dispatcher.report",
        "Dispatcher._apply_device_status", "Dispatcher._combine",
    }),
    "istio_tpu/runtime/fused.py": frozenset({
        "FusedPlan.packed_check", "FusedPlan.packed_report",
        "FusedPlan.packed_check_instep", "FusedPlan.narrow_batch",
        "FusedPlan.swap_warm_pending", "FusedPlan._serve_width",
    }),
    "istio_tpu/runtime/server.py": frozenset({
        "RuntimeServer.submit_report",
        "RuntimeServer._run_report_batch",
    }),
    "istio_tpu/runtime/device_quota.py": frozenset({
        "DeviceQuotaPool._flush",
    }),
    "istio_tpu/runtime/rulestats.py": frozenset({
        "RuleTelemetry.observe", "RuleTelemetry.add_host",
        "RuleTelemetry.sample", "RuleTelemetry.drain",
    }),
    "istio_tpu/canary/recorder.py": frozenset({
        "TrafficRecorder.tap",
    }),
    "istio_tpu/runtime/executor.py": frozenset({
        "HandlerLane.submit", "AdapterExecutor.submit",
        "AdapterExecutor.resolve",
    }),
    "istio_tpu/runtime/forensics.py": frozenset({
        "FlightRecorder.batch_begin", "FlightRecorder.stage_mark",
        "FlightRecorder.host_wait", "FlightRecorder.note_wire_decode",
        "FlightRecorder.note_batch", "FlightRecorder.note_direct",
        "FlightRecorder._capture", "EventTimeline.record",
        "EventTimeline._mergeable",
    }),
    "istio_tpu/sharding/router.py": frozenset({
        "ShardRouter.check", "ReplicaRouter.submit",
        "ReplicaRouter.lane_of",
    }),
    "istio_tpu/pilot/discovery.py": frozenset({
        "SnapshotCache.lookup", "SnapshotCache.peek",
        "SnapshotCache.store", "DiscoveryService._serve_cached",
        "DiscoveryService._generate_rds_batch",
    }),
    "istio_tpu/pilot/route_nfa.py": frozenset({
        "RouteScopeProgram.admit_rows",
    }),
}


@dataclasses.dataclass
class Violation:
    path: str
    line: int
    func: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.func}] {self.message}"


def lint_source(source: str, hot_names: frozenset[str],
                path: str = "<memory>") -> list[Violation]:
    """AST-lint one module's named hot functions (the pre-meshlint
    surface, kept for tests/tooling); detection delegates to
    meshlint's hot-path detector so there is exactly one definition
    of "host sync"."""
    from istio_tpu.analysis.meshlint.hotpath import sync_sites

    tree = ast.parse(source)
    lines = source.splitlines()
    out: list[Violation] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                if qual in hot_names:
                    for line, message in sync_sites(child, lines):
                        out.append(Violation(path, line, qual,
                                             message))
                else:
                    walk(child, f"{qual}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def main(root: str | None = None) -> int:
    root = root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    from istio_tpu.analysis.meshlint import run_meshlint

    report = run_meshlint(root=root, passes=("hotpath",))
    violations = [
        Violation(f.path, f.line, f.func, f.message)
        for f in report.findings]
    for v in violations:
        print(f"hotpath_lint: {v}")
    if not violations:
        print(f"hotpath_lint: ok "
              f"({report.stats.get('hot_reachable', 0)} inferred hot "
              f"functions from {report.stats.get('hot_roots', 0)} "
              f"roots clean)")
    return 1 if violations else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=None)
    sys.exit(main(root=ap.parse_args().root))
