"""Hot-path lint: ban host-sync calls in the serving batch-build/step
sections.

The serving hot path (batch build in `runtime/batcher.py`, the fused
check/report paths in `runtime/dispatcher.py`, the packed device trips
in `runtime/fused.py`) is engineered around ONE host<->device sync per
batch — every extra pull costs a full transport RTT (~120ms behind the
axon tunnel) and a stray `.item()` or `float(jnp_sum(...))` silently
serializes the pipeline. This AST lint walks the configured hot
functions and flags:

  * `.item()` calls and `jax.device_get` / `block_until_ready` —
    always a device sync;
  * `np.asarray(...)` / `np.array(...)` — a device pull when fed a
    device buffer (list/list-comp literals are auto-allowed);
  * `float()` / `int()` / `bool()` whose argument is a CALL expression
    (`float(x.sum())` syncs the computation it wraps);
  * blocking I/O on the flusher/dispatcher threads: `open`, `print`,
    `input`, `time.sleep`, subprocess/urllib/requests use.

Deliberate boundary crossings — THE designated pull, host-numpy work
after it — carry a `# hotpath: sync-ok` pragma on the offending line;
the lint enforces that every crossing is annotated, so a new sync in a
hot section is a conscious, reviewable decision, never an accident.

Usage: python scripts/hotpath_lint.py [--root DIR]   (exit 1 on
violations; tier-1 runs main() via tests/test_hotpath_lint.py)
"""
from __future__ import annotations

import argparse
import ast
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PRAGMA = "hotpath: sync-ok"

# file (repo-relative) → hot function qualnames (Class.method); the
# batch-build/step sections of the serving path. Additions here are
# the review surface when the hot path grows.
HOT_SECTIONS: dict[str, frozenset[str]] = {
    "istio_tpu/runtime/batcher.py": frozenset({
        "CheckBatcher.submit", "CheckBatcher._loop",
        "CheckBatcher._flush", "CheckBatcher._shed_stale",
        "CheckBatcher._run_one", "CheckBatcher._min_deadline",
        "CheckBatcher._drain_on_close",
    }),
    "istio_tpu/runtime/dispatcher.py": frozenset({
        "Dispatcher.check", "Dispatcher._check_fused",
        "Dispatcher._resolve", "Dispatcher._overlay_fallback",
        "Dispatcher._overlay_active",
        "Dispatcher._tensorize_for_device",
        "Dispatcher._ns_ids_from_batch",
        "Dispatcher._request_ns_ids",
        "Dispatcher._report_active_fused",
        # the report coalescer's dispatch leg (the telemetry
        # ingestion plane): runs on the report batcher's worker —
        # adapter fan-out and stage accounting only; the designated
        # device pulls live in _report_active_fused above
        "Dispatcher.report",
        "Dispatcher._apply_device_status", "Dispatcher._combine",
    }),
    "istio_tpu/runtime/fused.py": frozenset({
        "FusedPlan.packed_check", "FusedPlan.packed_report",
        "FusedPlan.packed_check_instep", "FusedPlan.narrow_batch",
        # swap-warm oracle bridge (PR 7): consulted on every served
        # batch by Dispatcher._check_fused — host-numpy tier routing
        # only, same pragma discipline as narrow_batch
        "FusedPlan.swap_warm_pending", "FusedPlan._serve_width",
    }),
    # report ingestion entries (the telemetry ingestion plane):
    # submit_report runs on pump/front threads (ack-after-enqueue —
    # the admission path must never sync or block), and
    # _run_report_batch is the coalescer worker's dispatch hook
    "istio_tpu/runtime/server.py": frozenset({
        "RuntimeServer.submit_report",
        "RuntimeServer._run_report_batch",
    }),
    # quota-plane flush (PR 7): the classic worker's device trip now
    # builds its tick/last staging under _lock INSIDE the _counts_lock
    # critical section (ordered with in-step session dispatch); its
    # designated pull and host-numpy kernel selection carry the only
    # sync-ok pragmas in the file
    "istio_tpu/runtime/device_quota.py": frozenset({
        "DeviceQuotaPool._flush",
    }),
    # rule-telemetry fold + drain (PR 4): observe/add_host/sample run
    # inside the batch step; drain's device→host pull is THE designated
    # boundary and carries the only sync-ok pragmas in the file
    "istio_tpu/runtime/rulestats.py": frozenset({
        "RuleTelemetry.observe", "RuleTelemetry.add_host",
        "RuleTelemetry.sample", "RuleTelemetry.drain",
    }),
    # canary recorder tap (PR 5): runs inside the dispatcher's check
    # hot sections (already linted above) on every served batch —
    # stride check + bounded tuple appends only. Corpus build / replay
    # / diff run at config-swap time, NOT here: the replay boundary
    # (canary/replay.py via the observe-off Dispatcher) is where the
    # device pulls live, behind dispatcher.py's existing pragmas.
    "istio_tpu/canary/recorder.py": frozenset({
        "TrafficRecorder.tap",
    }),
    # adapter-executor plane (ISSUE 12): submit runs once per host
    # action on the dispatcher's batch worker (breaker check + a
    # non-blocking queue put — never a wait), and resolve is THE
    # designated deadline-bounded fold boundary (its Event.wait is
    # the one place the batch may block on host work, bounded by the
    # request deadline). The reworked Dispatcher._overlay_active and
    # _check_fused host fold stay linted above.
    "istio_tpu/runtime/executor.py": frozenset({
        "HandlerLane.submit", "AdapterExecutor.submit",
        "AdapterExecutor.resolve",
    }),
    # tail-latency forensics (ISSUE 14): the flight recorder's tape
    # primitives run inside the batch step (batch_begin once per
    # batch, stage_mark per stage observation via the monitor tap,
    # host_wait per executor claim) and the capture path (note_batch /
    # note_direct / _capture) runs only for over-threshold requests —
    # all host-side dict/deque work; EventTimeline.record is called
    # from hot sections (quota _flush, breaker transitions) and must
    # stay a leaf-lock deque append. The serve boundaries (snapshot,
    # overlapping, capture_profile, thread_stacks) are scrape-rate.
    "istio_tpu/runtime/forensics.py": frozenset({
        "FlightRecorder.batch_begin", "FlightRecorder.stage_mark",
        "FlightRecorder.host_wait", "FlightRecorder.note_wire_decode",
        "FlightRecorder.note_batch", "FlightRecorder.note_direct",
        "FlightRecorder._capture", "EventTimeline.record",
        "EventTimeline._mergeable",
    }),
    # sharded serving plane (ISSUE 10): the shard router runs on every
    # lane's step worker (check = route + per-bank fused check + fold)
    # and the lane selector on every front thread's submit — host
    # string/dict work only; the banks' device pulls live behind
    # dispatcher.py's and fused.py's existing pragmas
    "istio_tpu/sharding/router.py": frozenset({
        "ShardRouter.check", "ReplicaRouter.submit",
        "ReplicaRouter.lane_of",
    }),
    # pilot discovery serving plane (ISSUE 15): cache lookup/store run
    # on every fleet poll (dict lookup + counters — a 10k-sidecar poll
    # storm rides these), _serve_cached is the per-call serve path and
    # _generate_rds_batch the batched generation leg (host JSON
    # assembly; its device step lives in route_nfa below)
    "istio_tpu/pilot/discovery.py": frozenset({
        "SnapshotCache.lookup", "SnapshotCache.peek",
        "SnapshotCache.store", "DiscoveryService._serve_cached",
        "DiscoveryService._generate_rds_batch",
    }),
    # batched source-admission device step (ISSUE 15): ONE pull per
    # batched generation — the np.asarray on the matched plane is THE
    # designated boundary and carries the file's only sync-ok pragma
    "istio_tpu/pilot/route_nfa.py": frozenset({
        "RouteScopeProgram.admit_rows",
    }),
}

_SYNC_ATTRS = ("item", "block_until_ready")
_PULL_FUNCS = {("np", "asarray"), ("np", "array"),
               ("numpy", "asarray"), ("numpy", "array"),
               ("jax", "device_get")}
_CAST_FUNCS = {"float", "int", "bool"}
_BLOCKING_NAMES = {"open", "input", "print", "breakpoint"}
_BLOCKING_ATTRS = {("time", "sleep")}
_BLOCKING_MODULES = {"subprocess", "urllib", "requests", "socket"}


@dataclasses.dataclass
class Violation:
    path: str
    line: int
    func: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.func}] {self.message}"


def _dotted(node: ast.AST) -> tuple[str, ...] | None:
    """Attribute/Name chain → ('np', 'asarray') etc."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class _HotVisitor(ast.NodeVisitor):
    def __init__(self, path: str, func: str, lines: list[str],
                 out: list[Violation]):
        self.path = path
        self.func = func
        self.lines = lines
        self.out = out

    def _pragma(self, node: ast.AST) -> bool:
        line = self.lines[node.lineno - 1] \
            if 0 < node.lineno <= len(self.lines) else ""
        return PRAGMA in line

    def _flag(self, node: ast.AST, message: str) -> None:
        if not self._pragma(node):
            self.out.append(Violation(self.path, node.lineno,
                                      self.func, message))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _SYNC_ATTRS:
                self._flag(node, f".{fn.attr}() is a host sync")
            chain = _dotted(fn)
            if chain is not None:
                if chain[-2:] in _PULL_FUNCS or chain in _PULL_FUNCS:
                    # list/list-comp literals are provably host-side
                    arg = node.args[0] if node.args else None
                    if not isinstance(arg, (ast.List, ast.ListComp)):
                        self._flag(node,
                                   f"{'.'.join(chain)}() pulls device "
                                   f"buffers to host")
                if chain[:2] in _BLOCKING_ATTRS \
                        or chain[0] in _BLOCKING_MODULES:
                    self._flag(node, f"blocking call "
                                     f"{'.'.join(chain)}()")
        elif isinstance(fn, ast.Name):
            if fn.id in _CAST_FUNCS and node.args \
                    and isinstance(node.args[0], ast.Call):
                self._flag(node, f"{fn.id}(<call>) syncs the wrapped "
                                 f"computation")
            if fn.id in _BLOCKING_NAMES:
                self._flag(node, f"blocking builtin {fn.id}()")
        self.generic_visit(node)


def lint_source(source: str, hot_names: frozenset[str],
                path: str = "<memory>") -> list[Violation]:
    """AST-lint one module's hot functions; importable for tests."""
    tree = ast.parse(source)
    lines = source.splitlines()
    out: list[Violation] = []

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                if qual in hot_names:
                    _HotVisitor(path, qual, lines, out).visit(child)
                else:
                    # nested defs inside a hot function are covered by
                    # the visitor above; nested hot names still match
                    walk(child, f"{qual}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def main(root: str | None = None) -> int:
    root = root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    violations: list[Violation] = []
    for rel, hot in sorted(HOT_SECTIONS.items()):
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as f:
            source = f.read()
        found = {name.split(".")[-1] for name in hot}
        present = set()
        tree = ast.parse(source)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                present.add(node.name)
        missing = found - present
        if missing:
            violations.append(Violation(
                rel, 1, "<config>",
                f"hot functions {sorted(missing)} no longer exist — "
                f"update HOT_SECTIONS"))
        violations.extend(lint_source(source, hot, rel))
    for v in violations:
        print(f"hotpath_lint: {v}")
    if not violations:
        n = sum(len(v) for v in HOT_SECTIONS.values())
        print(f"hotpath_lint: ok ({n} hot functions across "
              f"{len(HOT_SECTIONS)} files clean)")
    return 1 if violations else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=None)
    sys.exit(main(root=ap.parse_args().root))
