"""Roofline smoke: the CI gate for the roofline accounting layer
(compiler/roofline.py — ISSUE 6).

Three contracts pinned, FAIL (nonzero exit) on any breach:

1. SECTION KEYS — `_roofline_fields` (the helper every bench perf
   section routes through) emits `<prefix>fraction_of_roof` and a
   named `<prefix>bound` in {hbm, mxu, host} for the headline-,
   rbac-, full-mesh- and capacity-shaped engines. If a section's
   roofline ever silently degrades to its `*_roofline_error`
   fallback, CI catches it here, not in the next perf round.
2. EXACT BYTES — the model's prediction matches the COMPILED shapes
   exactly where exactness is well-defined: `h2d_batch` equals a real
   tensorized AttributeBatch's summed nbytes, `d2h_packed` equals a
   real packed_check pull's nbytes, and the index-tensor bytes inside
   the match components equal the live `RuleSetProgram.params`
   arrays' nbytes. No hand constants.
3. INTROSPECT — /debug/roofline serves the same model per serving
   bucket over real HTTP.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_roofline_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/roofline_smoke.py [--rules N]
"""
import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BOUNDS = ("hbm", "mxu", "host")


def _check_fields(failures: list, fields: dict, prefix: str) -> None:
    frac = fields.get(prefix + "fraction_of_roof")
    bound = fields.get(prefix + "bound")
    if not isinstance(frac, (int, float)) or not 0 <= frac <= 1:
        failures.append(
            f"{prefix}fraction_of_roof missing/out of range: {frac!r}"
            f" (error field: "
            f"{fields.get(prefix + 'roofline_error')!r})")
    if bound not in BOUNDS:
        failures.append(f"{prefix}bound missing/unnamed: {bound!r}")
    for key in ("bytes_per_step", "achieved_gbps", "roof_platform"):
        if prefix + key not in fields:
            failures.append(f"{prefix}{key} missing")


def main(n_rules: int = 64) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from istio_tpu.compiler import roofline
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.runtime.fused import build_fused_plan
    from istio_tpu.testing import workloads

    failures: list[str] = []
    batch = 64

    # ---- 1. every bench perf section's roofline fields ----
    engines = {}
    engines["headline_"] = workloads.make_engine(
        n_rules=n_rules, with_quota=True, jit=False)
    # capacity section: same engine family, no quota (bench parity)
    engines["capacity_"] = workloads.make_engine(
        n_rules=n_rules, with_quota=False, jit=False)
    snap = SnapshotBuilder(
        default_manifest=workloads.MESH_MANIFEST).build(
        workloads.make_rbac_store(8))
    engines["rbac_"] = build_fused_plan(snap).engine
    engines["full_mesh_"] = workloads.make_full_mesh(
        n_services=16, n_roles=4)[0]
    for prefix, engine in engines.items():
        fields = roofline.bench_fields(engine, batch, 1e-3, prefix)
        _check_fields(failures, fields, prefix)

    # ---- 2. bytes-per-step prediction matches compiled shapes ----
    engine = engines["headline_"]
    model = roofline.model_check_step(engine, batch)
    bags = workloads.make_bags(batch)
    ab = engine.tensorizer.tensorize(bags)
    actual_h2d = sum(int(np.asarray(a).nbytes) for a in (
        ab.ids, ab.present, ab.map_present, ab.str_bytes, ab.str_lens,
        ab.hash_ids))
    got = model.component("h2d_batch").bytes
    if got != actual_h2d:
        failures.append(f"h2d_batch model {got} != tensorized batch "
                        f"nbytes {actual_h2d}")
    # index-tensor bytes == the live device params' nbytes
    params = engine.ruleset.params
    g = engine.ruleset.geometry
    if g["n_fused_conjs"]:
        want = sum(int(np.asarray(params[k]).nbytes) for k in
                   ("eqc_col", "eqc_cid", "eqc_xor", "eqc_pad"))
        got = model.component("match_fused_eq").bytes \
            - batch * g["n_fused_conjs"] * (g["l_max_fused"] * 5 + 1)
        if got != want:
            failures.append(f"match_fused_eq index bytes {got} != "
                            f"params nbytes {want}")
    want = sum(int(np.asarray(params[k]).nbytes) for k in
               ("conj_m_idx", "conj_n_idx"))
    got = model.component("match_rules").bytes \
        - batch * g["n_rows"] * (2 * g["k_max"] + 3)
    if got != want:
        failures.append(f"match_rules index bytes {got} != params "
                        f"nbytes {want}")

    # d2h_packed == a real packed pull's nbytes (serving plan)
    store = workloads.make_store(max(n_rules // 2, 8))
    splan = build_fused_plan(SnapshotBuilder(
        default_manifest=workloads.MESH_MANIFEST).build(store))
    smodel = roofline.model_check_step(splan.engine, batch,
                                       plan=splan)
    sbatch = splan.engine.tensorizer.tensorize(
        workloads.make_bags(batch))
    packed = splan.packed_check(sbatch, np.zeros(batch, np.int32),
                                observe=False)
    got = smodel.component("d2h_packed").bytes
    if got != int(packed.nbytes):
        failures.append(f"d2h_packed model {got} != packed pull "
                        f"nbytes {int(packed.nbytes)}")
    if roofline.packed_pull_rows(splan) != packed.shape[0]:
        failures.append(
            f"packed_pull_rows {roofline.packed_pull_rows(splan)} != "
            f"pull rows {packed.shape[0]}")

    # ---- 3. /debug/roofline over real HTTP ----
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs

    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=64, buckets=(16, 64),
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    try:
        port = intro.start()
        srv.check_many(workloads.make_bags(8))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/roofline",
                timeout=10) as resp:
            payload = json.loads(resp.read())
        if "buckets" not in payload or "64" not in payload["buckets"]:
            failures.append(
                f"/debug/roofline missing bucket models: "
                f"{sorted(payload)}")
        else:
            entry = payload["buckets"]["64"]
            if entry.get("bytes_per_step", 0) <= 0:
                failures.append("/debug/roofline bucket 64 has no "
                                "bytes_per_step")
    finally:
        intro.close()
        srv.close()

    if failures:
        print("ROOFLINE SMOKE FAILED:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"roofline smoke ok: {len(engines)} sections keyed, exact "
          f"h2d/d2h/index bytes, /debug/roofline live")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=64)
    args = ap.parse_args()
    sys.exit(main(n_rules=args.rules))
