"""Decompose the small-batch (B=256) device step at 10k rules: full
engine step vs ruleset match alone vs standalone DFA kernels — where
does the <1ms p99 budget go?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    import jax
    import numpy as np

    import bench  # noqa: F401 (jax cache config)
    from istio_tpu.testing import workloads

    B = 256
    engine = workloads.make_engine(n_rules=10_000, with_quota=True,
                                   jit=False)
    bags = workloads.make_bags(2048)
    ab = jax.device_put(engine.tensorizer.tensorize(bags[:B]))
    req_ns = jax.device_put(np.asarray(
        workloads.make_request_ns(engine, 2048)[:B]))
    params = jax.device_put(engine.params)
    counts = engine.quota_counts
    sync = bench._roundtrip_s()
    print(f"sync {sync*1e3:.1f} ms")

    def timed(label, fn, n=120):
        out = fn()
        jax.block_until_ready(out)
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0 - sync) / n)
        print(f"{label:38s} {best*1e3:8.3f} ms")
        return best

    step = jax.jit(engine.raw_step)

    def full():
        v, c = step(params, ab, req_ns, counts)
        return v.status
    timed("full engine step", full)

    rs_fn = jax.jit(engine.ruleset.fn)

    def match_only():
        m, nm, e = rs_fn(params, ab)
        return m
    timed("ruleset match only", match_only)

    # standalone DFA banks at this batch size, both formulations
    from istio_tpu.ops import bytes_ops
    from istio_tpu.ops.regex_dfa import (compile_regex, pack_dfas,
                                         pack_dfas_classes,
                                         pack_dfas_onehot)
    pats = ([f"^/(products|reviews)/[0-9]+/v{k}$" for k in range(4)])
    dfas = [compile_regex(p) for p in pats]
    trans, accept = pack_dfas(dfas)
    classes = pack_dfas_classes(dfas)
    packed = pack_dfas_onehot(dfas, classes)
    data = jax.device_put(np.asarray(ab.str_bytes)[:, 0, :])
    lens = jax.device_put(np.asarray(ab.str_lens)[:, 0])
    trans_j = jax.device_put(trans)
    accept_j = jax.device_put(accept)
    gather = jax.jit(lambda: bytes_ops.dfa_match_many(
        data, lens, trans_j, accept_j))
    timed(f"dfa gather bank ({len(dfas)} pats)", gather)
    onehot = jax.jit(lambda: bytes_ops.dfa_match_many_onehot(
        data, lens, packed))
    timed(f"dfa onehot bank ({len(dfas)} pats)", onehot)
    print("n_states", classes["n_states"], "n_classes",
          classes["n_classes"])
