"""Latency smoke: the CI gate that the measured wire-to-verdict
latency plane (ISSUE 13) actually works end to end.

Boots a RuntimeServer with the full latency plane ON (continuous
batching, check-cache grants, zero-copy wire decode when the shim
toolchain is present) behind the REAL C++ HTTP/2 front, drives it
with the C++ closed-loop client, and FAILS (nonzero exit) unless:

  1. the WIRE HISTOGRAM measures: a closed-loop window's histogram
     delta carries every completion, p50/p95/p99 are present, finite
     and ordered, and the client's independent per-request p99
     (h2load's exact latency vector, its own clock) agrees to within
     a generous cross-clock bound;
  2. ZERO-COPY PARITY over HTTP: verdicts served through the native
     front's wire-decode path match the in-process host-oracle
     verdicts status-for-status on the same requests (when the shim
     toolchain is absent the python fallback serves — the parity
     assert still bites, the staging asserts are skipped and the
     fallback is reported);
  3. the CONTINUOUS-BATCHING lane NEVER serves a stale generation
     across a config swap: a probe path flips OK → PERMISSION_DENIED
     via a live store delta under closed-loop load; once the new
     generation's verdict is observed, NO later response reverts —
     and the post-swap grant TTL sits at the floor (revocation);
  4. the grant plane funds a caching client: a MixerClient on repeat
     traffic sees ≥90% cache hits against the live native front.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_latency_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/latency_smoke.py [--rules N]
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PROBE = {"destination.service": "probe.ns1.svc.cluster.local",
         "request.path": "/admin/probe"}


def _fail(msg: str) -> int:
    print(f"LATENCY SMOKE FAIL: {msg}")
    return 1


def main(n_rules: int = 120, n_loop: int = 300) -> int:
    from istio_tpu.api import MixerClient
    from istio_tpu.api.native_server import NativeMixerServer
    from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import perf, workloads

    store = workloads.make_store(n_rules)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.001, max_batch=64, buckets=(16, 64),
        continuous_batching=True,
        check_grants=True,
        grant_ttl_floor_s=0.3, grant_ttl_cap_s=1.5,
        grant_ttl_ramp_per_s=2.0,
        default_manifest=workloads.MESH_MANIFEST))
    native = NativeMixerServer(srv, max_batch=64, min_fill=8,
                               window_us=1000, pumps=2,
                               continuous=True)
    try:
        port = native.start()
        dicts = workloads.make_request_dicts(64)
        payloads = perf.make_check_payloads(dicts)

        # ---- leg 1: the wire histogram measures under closed loop --
        perf.run_h2load(port, payloads, 60, 16, 0.3)      # warm
        base = native.latency_raw()
        rep = perf.run_h2load(port, payloads, n_loop, 16, 0.2)
        snap = native.latency_snapshot(since=base)
        for k in ("p50", "p95", "p99"):
            v = snap.get(k)
            if v is None or not (0.0 < v < 60_000.0):
                return _fail(f"wire histogram {k} absent/infinite: "
                             f"{snap}")
        if not snap["p50"] <= snap["p95"] <= snap["p99"]:
            return _fail(f"wire quantiles unordered: {snap}")
        if snap["n"] < n_loop:
            return _fail(f"wire histogram missed completions: "
                         f"n={snap['n']} < {n_loop}")
        # independent client-side check: two clocks, two codebases.
        # The client p99 includes its own queueing; the wire p99 must
        # not EXCEED it wildly (same requests, inner window)
        if not (snap["p99"] <= rep["p99_ms"] * 3.0 + 5.0):
            return _fail(
                f"wire p99 {snap['p99']}ms vs client p99 "
                f"{rep['p99_ms']}ms disagree beyond cross-clock skew")
        print(f"latency-smoke: wire p50/p95/p99 = {snap['p50']}/"
              f"{snap['p95']}/{snap['p99']} ms over {snap['n']} "
              f"requests (client p99 {rep['p99_ms']} ms)")

        # ---- leg 2: decode parity over HTTP vs the host oracle -----
        plan = srv.controller.dispatcher.fused
        native_decode = plan is not None and plan.native is not None
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        try:
            from istio_tpu.attribute.bag import bag_from_mapping
            probe_dicts = dicts[:24]
            got = [client.check(dict(d)).precondition.status.code
                   for d in probe_dicts]
            want = [r.status_code
                    for r in srv.controller.dispatcher
                    .check_host_oracle([bag_from_mapping(d)
                                        for d in probe_dicts])]
            if got != want:
                return _fail(f"wire-decode verdicts diverge from the "
                             f"host oracle: {got} vs {want}")
            if native_decode:
                st = plan.native.staging_stats()
                if st["staged_decodes"] <= 0:
                    return _fail("shim present but the zero-copy "
                                 f"decoder never ran: {st}")
                print(f"latency-smoke: zero-copy decode parity ok "
                      f"({st['staged_decodes']} staged decodes over "
                      f"shapes {sorted(st['shapes'])})")
            else:
                print("latency-smoke: shim toolchain absent — python "
                      "wire-decode fallback served; parity ok")
        finally:
            client.close()

        # ---- leg 3: no stale generation across a config swap -------
        probe_client = MixerClient(f"127.0.0.1:{port}",
                                   enable_check_cache=False)
        stop_load = threading.Event()
        load_err: list = []

        def _bg_load() -> None:
            while not stop_load.is_set():
                try:
                    perf.run_h2load(port, payloads, 100, 8, 0.0)
                except Exception as exc:   # surfaced after join
                    load_err.append(exc)
                    return

        loader = threading.Thread(target=_bg_load, daemon=True)
        loader.start()
        try:
            if probe_client.check(dict(PROBE)) \
                    .precondition.status.code != OK:
                return _fail("probe path must start OK")
            gen0 = srv.grants.generation
            store.set(("handler", "istio-system", "probe-deny"), {
                "adapter": "denier",
                "params": {"status_code": PERMISSION_DENIED,
                           "status_message": "probe flipped",
                           "valid_duration_s": 600.0}})
            store.set(("instance", "istio-system", "probe-nothing"), {
                "template": "checknothing", "params": {}})
            store.set(("rule", "istio-system", "probe-rule"), {
                "match": 'request.path.startsWith("/admin/probe")',
                "actions": [{"handler": "probe-deny",
                             "instances": ["probe-nothing"]}]})
            deadline = time.time() + 60.0
            flipped = False
            while time.time() < deadline:
                r = probe_client.check(dict(PROBE))
                if r.precondition.status.code == PERMISSION_DENIED:
                    flipped = True
                    # post-swap grant must be REVOKED: generation
                    # bumped, and the served TTL within the policy's
                    # ramp bound for the observed revocation age (a
                    # slow CI runner may observe the flip a quantum
                    # or two after the revoke — the bound follows the
                    # quantized ramp instead of racing it)
                    ttl = r.precondition.valid_duration \
                        .ToTimedelta().total_seconds()
                    if srv.grants.generation <= gen0:
                        return _fail("flip served before grant "
                                     "revocation")
                    g = srv.grants
                    age_q = (g.stats()["global_age_s"]
                             // g.quantum_s) * g.quantum_s \
                        if g.quantum_s > 0 else \
                        g.stats()["global_age_s"]
                    allowed = min(g.ttl_cap_s,
                                  g.ttl_floor_s
                                  + age_q * g.ttl_ramp_per_s)
                    if not ttl <= allowed + 0.05:
                        return _fail(
                            f"post-swap TTL {ttl} exceeds the "
                            f"revoked ramp bound {allowed:.2f} "
                            "(revocation broken)")
                    break
                time.sleep(0.02)
            if not flipped:
                return _fail("config swap never took effect at the "
                             "wire")
            # once the new generation is observed, NO response may
            # revert to the old verdict — the continuous lane must
            # resolve the dispatcher per batch, never cache a
            # generation across the swap
            for i in range(50):
                code = probe_client.check(dict(PROBE)) \
                    .precondition.status.code
                if code != PERMISSION_DENIED:
                    return _fail(f"STALE GENERATION: response {i} "
                                 f"reverted to code {code} after the "
                                 "swap was observed")
            print("latency-smoke: config swap monotonic at the wire "
                  "(50/50 post-flip responses on the new generation)")
        finally:
            stop_load.set()
            loader.join(timeout=30)
            probe_client.close()
        if load_err:
            return _fail(f"background load failed during the swap: "
                         f"{load_err[0]}")

        # ---- leg 4: grants fund a caching client -------------------
        gclient = MixerClient(f"127.0.0.1:{port}",
                              enable_check_cache=True)
        try:
            rep_dicts = dicts[:8]
            for d in rep_dicts:
                gclient.check(dict(d))
            for i in range(160):
                gclient.check(dict(rep_dicts[i % len(rep_dicts)]))
            st = gclient.cache_stats
            rate = st["hits"] / max(st["hits"] + st["misses"], 1)
            if rate < 0.90:
                return _fail(f"client cache hit rate {rate:.3f} < "
                             f"0.90 ({st})")
            print(f"latency-smoke: client cache hit rate "
                  f"{rate:.3f} ({st})")
        finally:
            gclient.close()

        print("LATENCY SMOKE OK")
        return 0
    finally:
        native.stop()
        srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=120)
    ap.add_argument("--loop", type=int, default=300)
    a = ap.parse_args()
    sys.exit(main(n_rules=a.rules, n_loop=a.loop))
