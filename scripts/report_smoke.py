"""Report-plane smoke: drive Mixer/Report end-to-end over real HTTP
(the C++ native wire when the toolchain builds, the python gRPC front
otherwise — both are real HTTP/2), and FAIL (nonzero exit) unless

  1. record conservation is EXACT: N records sent == records the
     adapter actually received == records the plane counted exported,
     with zero rejections (accepted == exported + rejected is the
     ingestion plane's correctness invariant — an acked record must
     never silently vanish behind the ack-after-enqueue contract);
  2. every stage of the six-stage report pipeline decomposition
     (wire_decode → coalesce_wait → tensorize → device_field_eval →
     intern_decode → adapter_dispatch) recorded observations;
  3. /debug/report serves over HTTP and agrees with the in-process
     conservation counters;
  4. a bounded coalescer under overflow sheds TYPED
     RESOURCE_EXHAUSTED at the wire (the client sees the honest
     status code) and conservation stays exact through the overload:
     accepted == exported + rejected with rejected > 0, nothing
     dropped silently.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_report_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/report_smoke.py \
           [--rules N] [--rpcs N] [--records N] [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class CountingHandler:
    """Wraps the built report adapter: counts every instance it
    receives (the 'adapter records out' side of the conservation
    check) and can block dispatch (the overflow leg's way to wedge
    the coalescer deterministically)."""

    def __init__(self, inner=None, block: threading.Event | None = None):
        self.inner = inner
        self.block = block
        self.records = 0
        self.calls = 0
        self._lock = threading.Lock()

    def handle_report(self, template, instances) -> None:
        if self.block is not None:
            self.block.wait(timeout=60)
        with self._lock:
            self.records += len(instances)
            self.calls += 1
        if self.inner is not None:
            self.inner.handle_report(template, instances)


def _start_front(srv, failures: list) -> tuple:
    """(port, stop_fn, front_name): the native C++ wire when the
    toolchain builds, else the python gRPC front — both real HTTP/2,
    so the smoke always runs end-to-end over a socket."""
    try:
        from istio_tpu.api.native_server import NativeMixerServer
        native = NativeMixerServer(srv, pumps=1)
        port = native.start()
        return port, native.stop, "native"
    except Exception as exc:
        print(f"report smoke: native front skipped: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        from istio_tpu.api.grpc_server import MixerGrpcServer
        g = MixerGrpcServer(runtime=srv)
        port = g.start()
        return port, g.stop, "grpc"


def _drain(monitor, base, deadline_s: float = 30.0) -> dict:
    end = time.time() + deadline_s
    cons = monitor.report_conservation(since=base)
    while time.time() < end:
        cons = monitor.report_conservation(since=base)
        if cons["in_flight"] == 0:
            break
        time.sleep(0.02)
    return cons


def main(n_rules: int = 12, n_rpcs: int = 4, records_per_rpc: int = 8,
         seed: int = 3) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.api.client import MixerClient
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs, monitor
    from istio_tpu.testing import workloads

    failures: list[str] = []

    # ---- leg 1: exact conservation + full stage decomposition ------
    store = workloads.make_store(n_rules, seed=seed)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    client = None
    stop_front = None
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 16))
        intro_port = intro.start()
        # count at the adapter: make_store's report-all rule fires one
        # reqcount metric instance per record into prom.istio-system
        d = srv.controller.dispatcher
        counting = CountingHandler(inner=None)
        d.handlers["prom.istio-system"] = counting
        port, stop_front, front = _start_front(srv, failures)
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        dicts = workloads.make_request_dicts(
            n_rpcs * records_per_rpc, seed=seed)
        base = monitor.report_conservation()
        stage_base = monitor.report_stage_baseline()
        for i in range(n_rpcs):
            client.report(dicts[i * records_per_rpc:
                                (i + 1) * records_per_rpc])
        n_sent = n_rpcs * records_per_rpc
        cons = _drain(monitor, base)

        if cons["in_flight"] != 0:
            failures.append(f"report plane failed to drain: {cons}")
        if cons["accepted"] != n_sent:
            failures.append(f"accepted {cons['accepted']} != "
                            f"{n_sent} records sent")
        if cons["exported"] != n_sent or cons["rejected_total"] != 0:
            failures.append(
                f"conservation violated: {n_sent} in, "
                f"{cons['exported']} exported + "
                f"{cons['rejected_total']} rejected")
        if counting.records != n_sent:
            failures.append(
                f"adapter saw {counting.records} records, "
                f"{n_sent} sent — a record was dropped or duplicated "
                f"between the {front} wire and the adapter")
        if not cons["exact"]:
            failures.append(f"conservation not exact: {cons}")

        # every pipeline stage must have recorded observations
        stages = monitor.report_latency_snapshot(
            since=stage_base)["stages"]
        for stage in monitor.REPORT_STAGES:
            if stages.get(stage, {}).get("count", 0) <= 0:
                failures.append(
                    f"stage histogram empty: {stage} (observed: "
                    f"{sorted(stages)})")

        # /debug/report serves and agrees with the counters
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro_port}/debug/report",
                timeout=30) as r:
            view = json.loads(r.read().decode())
        for key in ("stages", "conservation", "coalescer",
                    "recent_drops", "templates"):
            if key not in view:
                failures.append(f"/debug/report missing '{key}'")
        vc = view.get("conservation", {})
        live = monitor.report_conservation()
        if vc.get("accepted") != live["accepted"] or \
                vc.get("exported") != live["exported"]:
            failures.append(
                f"/debug/report conservation {vc} disagrees with "
                f"the live counters {live}")
        if view.get("templates", {}).get("metric", 0) < n_sent:
            failures.append(
                f"/debug/report per-template counts missed the "
                f"metric records: {view.get('templates')}")
    finally:
        try:
            if client is not None:
                client.close()
            if stop_front is not None:
                stop_front()
        finally:
            intro.close()
            srv.close()

    # ---- leg 2: overflow sheds TYPED at the wire -------------------
    import grpc

    block = threading.Event()
    store2 = workloads.make_store(n_rules, seed=seed + 1)
    srv2 = RuntimeServer(store2, ServerArgs(
        batch_window_s=0.0005, max_batch=4, buckets=(4,),
        report_queue_cap=4, pipeline=1,
        default_manifest=workloads.MESH_MANIFEST))
    client2 = None
    stop2 = None
    try:
        plan2 = srv2.controller.dispatcher.fused
        if plan2 is not None:
            plan2.prewarm((4,))
        d2 = srv2.controller.dispatcher
        blocking = CountingHandler(inner=None, block=block)
        d2.handlers["prom.istio-system"] = blocking
        port2, stop2, front2 = _start_front(srv2, failures)
        client2 = MixerClient(f"127.0.0.1:{port2}",
                              enable_check_cache=False)
        dicts2 = workloads.make_request_dicts(64, seed=seed)
        base2 = monitor.report_conservation()
        shed_code = None
        # the first batch dispatches and wedges in the blocked
        # adapter; the bounded queue (cap 4) then fills, and an
        # overflowing RPC must answer typed RESOURCE_EXHAUSTED.
        # Wire-driven only on the NATIVE front (ack-after-enqueue:
        # RPCs return immediately, so one client can outrun the
        # queue); the grpc front's sync Report BLOCKS until dispatch
        # — a sequential client would wait out the wedged adapter
        # ~60s per RPC and never fill the cap, so there the overflow
        # is driven in-process via submit_report and the typed
        # exception's wire mapping (grpc_code) is asserted instead
        if front2 == "native":
            for i in range(64):
                try:
                    client2.report(
                        dicts2[(4 * i) % 64:(4 * i) % 64 + 4])
                except grpc.RpcError as exc:
                    shed_code = exc.code()
                    break
                time.sleep(0.01)
            want = grpc.StatusCode.RESOURCE_EXHAUSTED
        else:
            from istio_tpu.attribute.bag import bag_from_mapping
            from istio_tpu.runtime.resilience import (
                RESOURCE_EXHAUSTED, ResourceExhaustedError)
            for i in range(200):
                futs = srv2.submit_report(
                    [bag_from_mapping(d)
                     for d in dicts2[(2 * i) % 64:(2 * i) % 64 + 2]])
                exc = next((f.exception() for f in futs
                            if f.done() and f.exception()), None)
                if isinstance(exc, ResourceExhaustedError):
                    shed_code = exc.grpc_code
                    break
            want = RESOURCE_EXHAUSTED
        if shed_code is None:
            failures.append(
                "bounded report queue (cap 4) never shed a typed "
                "rejection under a wedged adapter")
        elif shed_code != want:
            failures.append(
                f"overflow shed the WRONG code: {shed_code} "
                f"(want {want}) on the {front2} front")
        block.set()   # release the adapter; the backlog drains
        cons2 = _drain(monitor, base2)
        if cons2["in_flight"] != 0 or not cons2["exact"]:
            failures.append(
                f"overflow leg failed to drain exactly: {cons2}")
        if cons2["rejected"].get("queue_full", 0) <= 0:
            failures.append(
                f"no queue_full rejections counted through the "
                f"overload: {cons2}")
        if cons2["accepted"] != cons2["exported"] + \
                cons2["rejected_total"]:
            failures.append(
                f"overflow conservation violated: {cons2}")
        # drop reasons surfaced for the operator
        drops = monitor.report_counters()["recent_drops"]
        if not any(dr["reason"] == "queue_full" for dr in drops):
            failures.append(
                "recent_drops carries no queue_full entry after the "
                "overflow leg")
    finally:
        block.set()
        try:
            if client2 is not None:
                client2.close()
            if stop2 is not None:
                stop2()
        finally:
            srv2.close()

    if failures:
        print("REPORT SMOKE FAILURES:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print(f"report smoke OK: {n_rpcs}x{records_per_rpc} records "
          f"conserved exactly, six stages observed, /debug/report "
          f"serves, overflow sheds typed")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=12)
    ap.add_argument("--rpcs", type=int, default=4)
    ap.add_argument("--records", type=int, default=8)
    ap.add_argument("--seed", type=int, default=3)
    a = ap.parse_args()
    sys.exit(main(n_rules=a.rules, n_rpcs=a.rpcs,
                  records_per_rpc=a.records, seed=a.seed))
