"""Shard smoke: compile a seeded ≥100k-rule fleet snapshot into K
namespace shards, serve Zipf-skewed traffic through the replica-
parallel router over a REAL front (python gRPC), and FAIL (nonzero
exit) unless

  1. the sharded path's verdicts are EXACTLY what the compiler's
     SnapshotOracle derives (istio_tpu/sharding/parity.py:
     per-visible-rule OracleProgram evaluation + the shared
     fused_check_status decision derivation) — status codes over the
     wire, status + GLOBAL deny-rule attribution in-process (the fold
     must remap bank-local deny indices);
  2. zero rows are dropped or misrouted: every sent request is
     answered, router misroute counters are zero, and per-bank routed
     rows sum to exactly the rows served;
  3. the plan is sane: every config rule lives in exactly one bank
     (global rules replicated into all K), and LPT balance holds
     under the documented namespace skew;
  4. /debug/shards agrees with the routers (occupancy, bank rule
     counts, stage decomposition non-empty after traffic).

The monolithic device program is never warmed or executed — the whole
point of the plane is that a 100k-rule snapshot serves WITHOUT its
monolithic XLA compile. Rule telemetry is off here (a 100k-row ×
500-namespace accumulator plane is its own scale project; the
sharding telemetry fan is covered at unit scale in
tests/test_sharding.py).

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_shard_smoke.py) at the full 100k-rule scale.

Usage: JAX_PLATFORMS=cpu python scripts/shard_smoke.py \
           [--rules N] [--namespaces N] [--shards K] [--replicas N] \
           [--checks N] [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(n_rules: int = 100_000, n_namespaces: int = 512,
         shards: int = 8, replicas: int = 2, n_checks: int = 48,
         seed: int = 7) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import time

    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.sharding import oracle_check_statuses
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    t0 = time.perf_counter()
    store = workloads.make_fleet_store(n_rules, n_namespaces, seed)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(16,),
        shards=shards, replicas=replicas,
        rule_telemetry=False, initial_prewarm=False,
        default_manifest=workloads.MESH_MANIFEST))
    build_s = time.perf_counter() - t0
    intro = IntrospectServer(runtime=srv)
    g = MixerGrpcServer(runtime=srv)
    client = None
    try:
        state = srv._sharded
        plan = state["plan"]
        banks = state["banks"]
        snap = srv.controller.dispatcher.snapshot
        n_cfg = len(snap.rules)

        # -- plan sanity: exact coverage + replication accounting ----
        if state["mode"] != "sharded":
            failures.append(f"expected sharded mode, got "
                            f"{state['mode']} "
                            f"({state['fallback_reason']})")
        if len(banks) != shards:
            failures.append(f"{len(banks)} banks != {shards} shards")
        n_global = len(plan.global_rules)
        covered = sum(len(r) for r in plan.shard_rules)
        want = n_cfg + (shards - 1) * n_global
        if covered != want:
            failures.append(
                f"plan covers {covered} rule slots, expected {want} "
                f"({n_cfg} rules + {shards - 1}x{n_global} replicated "
                f"globals) — a rule is dropped or double-assigned")
        seen: set[int] = set()
        for rs_ in plan.shard_rules:
            seen.update(rs_)
        if len(seen) != n_cfg:
            failures.append(f"plan reaches {len(seen)} distinct rules "
                            f"of {n_cfg}")
        bal = plan.balance()
        if bal["max_over_mean_cost"] > 2.0:
            failures.append(f"shard balance {bal['max_over_mean_cost']}"
                            f"x max/mean — LPT packing regressed "
                            f"(per-shard costs {bal['cost_per_shard']})")

        # -- serve through the real front ----------------------------
        intro_port = intro.start()
        grpc_port = g.start()
        client = MixerClient(f"127.0.0.1:{grpc_port}",
                             enable_check_cache=False)
        dicts = workloads.make_fleet_traffic(
            n_checks, n_rules, n_namespaces, seed)
        wire_codes = []
        for d in dicts:
            resp = client.check(d)
            wire_codes.append(int(resp.precondition.status.code))
        if len(wire_codes) != len(dicts):
            failures.append(f"dropped rows at the wire: "
                            f"{len(wire_codes)}/{len(dicts)} answered")

        # -- in-process pass (deny_rule fold remap is judged here) ---
        bags = [bag_from_mapping(d) for d in dicts]
        local = srv.check_many(bags)

        # -- EXACT SnapshotOracle parity -----------------------------
        t_or = time.perf_counter()
        plan_fused = srv.controller.dispatcher.fused
        expected = oracle_check_statuses(snap, plan_fused, bags)
        oracle_s = time.perf_counter() - t_or
        n_deny = 0
        for i, (want_r, got, code) in enumerate(
                zip(expected, local, wire_codes)):
            if got.status_code != want_r["status"]:
                failures.append(
                    f"row {i}: sharded status {got.status_code} != "
                    f"oracle {want_r['status']}")
            if code != want_r["status"]:
                failures.append(
                    f"row {i}: wire status {code} != oracle "
                    f"{want_r['status']}")
            if got.deny_rule != want_r["deny_rule"]:
                failures.append(
                    f"row {i}: folded deny_rule {got.deny_rule} != "
                    f"oracle global index {want_r['deny_rule']}")
            if want_r["status"] != 0:
                n_deny += 1
            if len(failures) > 16:
                break
        if not n_deny:
            failures.append("oracle saw zero denies — the fleet "
                            "traffic no longer exercises deny rules")

        # -- zero dropped / misrouted rows ---------------------------
        routing = srv.batcher.routing_stats()
        mis = routing["misrouted"]
        if mis:
            failures.append(f"{mis} misrouted rows")
        routed = routing["rows_total"]
        served = len(wire_codes) + len(bags)
        if routed != served:
            failures.append(f"router row conservation: routed "
                            f"{routed} != served {served}")

        # -- /debug/shards agreement ---------------------------------
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro_port}/debug/shards",
                timeout=30) as r:
            view = json.loads(r.read().decode())
        if not view.get("enabled"):
            failures.append("/debug/shards reports disabled on a "
                            "sharded server")
        if view.get("misrouted") != 0:
            failures.append(f"/debug/shards misrouted = "
                            f"{view.get('misrouted')}")
        vrows = sum(view.get("rows_per_shard", {}).values())
        if vrows != routed:
            failures.append(f"/debug/shards rows {vrows} != router "
                            f"rows {routed}")
        vbanks = {b["shard"]: b["rules"] for b in view.get("banks", ())}
        for k in range(shards):
            if vbanks.get(k) != len(plan.shard_rules[k]):
                failures.append(
                    f"/debug/shards bank {k} rules {vbanks.get(k)} != "
                    f"plan {len(plan.shard_rules[k])}")
        stages = view.get("stages", {})
        for stage in ("shard_dispatch", "bank_check", "fold"):
            if not stages.get(stage, {}).get("count"):
                failures.append(f"shard stage {stage!r} has no "
                                f"observations after traffic")
    finally:
        if client is not None:
            client.close()
        g.stop()
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"shard smoke ok: {n_rules} rules / {n_namespaces} ns "
              f"-> {shards} shards x {replicas} replicas "
              f"(build {build_s:.1f}s), {len(wire_codes)} wire + "
              f"{len(bags)} local checks, EXACT oracle parity "
              f"({n_deny} denies, recount {oracle_s:.1f}s), "
              f"0 dropped/misrouted, balance "
              f"{bal['max_over_mean_cost']}x")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=100_000)
    ap.add_argument("--namespaces", type=int, default=512)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--checks", type=int, default=48)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.namespaces, args.shards,
                  args.replicas, args.checks, args.seed))
