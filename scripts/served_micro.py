"""Per-request host-cost decomposition for the served path.

Times each stage of one Check RPC's server-side Python work in
isolation (no device, no grpc): top-level request split, response
proto build + serialize, quota instance build (with its lazy wire
decode), and payload issue cost on the client side.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N = 3000


def timeit(label, fn, n=N):
    fn()   # warm
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    dt = (time.perf_counter() - t0) / n
    print(f"{label:45s} {dt * 1e6:9.1f} us/req  ({1 / dt:9.0f}/s)")
    return dt


if __name__ == "__main__":
    from istio_tpu.api.wire import LazyWireBag, RawCheckRequest, \
        referenced_to_proto
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.testing import perf, workloads

    dicts = workloads.make_request_dicts(512)
    payloads = perf.make_check_payloads(dicts, quota_every=4)
    pq = payloads[0]      # has quota
    pn = payloads[1]      # no quota

    timeit("RawCheckRequest parse (no quota)", lambda: RawCheckRequest(pn))
    timeit("RawCheckRequest parse (with quota)",
           lambda: RawCheckRequest(pq))

    req = RawCheckRequest(pn)
    timeit("LazyWireBag construct", lambda: LazyWireBag(
        req.attributes_raw, None, native_ok=True))
    timeit("LazyWireBag full decode", lambda: LazyWireBag(
        req.attributes_raw, None, native_ok=True)._decode())

    # response build + serialize (the no-quota common case)
    import datetime

    ref = pb.ReferencedAttributes()

    def build_resp():
        resp = pb.CheckResponse()
        resp.precondition.status.code = 0
        resp.precondition.valid_duration.FromTimedelta(
            datetime.timedelta(seconds=60))
        resp.precondition.valid_use_count = 10000
        resp.precondition.referenced_attributes.CopyFrom(ref)
        return resp.SerializeToString()
    timeit("CheckResponse build+serialize", build_resp)

    # quota instance build over a lazy bag (the 25% path)
    store = workloads.make_store(200)
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    srv = RuntimeServer(store, ServerArgs(
        default_manifest=workloads.MESH_MANIFEST, fused=False))
    snap = srv.controller.dispatcher.snapshot
    inst_q = [k for k in snap.instances if k.startswith("rq")]
    if not inst_q:
        inst_q = list(snap.instances)
    inst = snap.instances[inst_q[0]]

    def build_inst():
        bag = LazyWireBag(req.attributes_raw, None, native_ok=True)
        return inst.build(bag)
    timeit("quota instance build (lazy bag, cold)", build_inst)

    bag_warm = LazyWireBag(req.attributes_raw, None, native_ok=True)
    bag_warm._decode()
    timeit("quota instance build (decoded bag)",
           lambda: inst.build(bag_warm))
    srv.close()
