"""Rulestats smoke: serve a seeded check mix through BOTH real fronts
(python gRPC + the C++ native wire), drain the on-device per-rule
accumulators, and FAIL (nonzero exit) unless

  1. the drained per-rule hit/deny/error counts EXACTLY equal an
     independent oracle recount of the same traffic (telemetry is a
     measurement, not an estimate),
  2. the /debug/rulestats introspect view agrees with the aggregator
     (top-rule counts, never-hit bookkeeping), and
  3. the adapter export path agrees: a prometheus adapter handler
     registered as a rulestats exporter ends up with the same per-rule
     totals in its scrape output.

The oracle recount walks every request through the compiler's
SnapshotOracle (the same conformance oracle the device programs are
pinned against) and re-derives deny attribution from the snapshot's
fused action metadata — denier statuses and STRINGS-list membership —
in device combine order (lowest rule index wins). The native front is
fail-soft: a missing C++ toolchain skips that half with a note (the
grpc half must still pass).

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_rulestats_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/rulestats_smoke.py \
           [--rules N] [--checks N] [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def oracle_recount(snapshot, plan, bags,
                   identity_attr: str = "destination.service"
                   ) -> tuple[dict, dict, dict]:
    """Independent per-rule recount over `bags` → ({rule idx: hits},
    {rule idx: denies}, {rule idx: errors}), matching the telemetry
    plane's semantics exactly:

      hits    — rule namespace-visible AND predicate matched
      denies  — rule is the LOWEST-index active rule whose fused check
                action produces a non-OK status (device combine order)
      errors  — rule namespace-visible AND predicate raised

    Deny attribution re-derives the fused action semantics from the
    snapshot via compiler/ruleset.fused_check_status (denier params,
    STRINGS list membership with the blacklist→PERMISSION_DENIED /
    whitelist-miss→NOT_FOUND / absent→INTERNAL codes of
    models/policy_engine) — independent of the device path being
    verified, and the SAME derivation the canary's exemplar
    confirmation uses. Shared by this smoke and the
    tests/test_rulestats.py property tests."""
    from istio_tpu.compiler.ruleset import (SnapshotOracle,
                                            fused_check_status)
    from istio_tpu.runtime.dispatcher import _namespace_of

    rs = snapshot.ruleset
    n_cfg = len(snapshot.rules)
    oracle = SnapshotOracle(
        rs.rules[:n_cfg], snapshot.finder,
        seed={r: p for r, p in rs.host_fallback.items() if r < n_cfg})
    hits: dict[int, int] = {}
    denies: dict[int, int] = {}
    errors: dict[int, int] = {}

    def fused_status(ridx: int, bag) -> int:
        return fused_check_status(snapshot, plan, ridx, bag)

    for bag in bags:
        req_ns = _namespace_of(bag, identity_attr)
        deny_done = False
        for ridx, rule in enumerate(oracle.rules):
            if rule.namespace and rule.namespace != req_ns:
                continue
            try:
                m = bool(oracle._prog(ridx).evaluate(bag))
            except Exception:
                errors[ridx] = errors.get(ridx, 0) + 1
                continue
            if not m:
                continue
            hits[ridx] = hits.get(ridx, 0) + 1
            if not deny_done and fused_status(ridx, bag) != 0:
                denies[ridx] = denies.get(ridx, 0) + 1
                deny_done = True
    return hits, denies, errors


def make_traffic(n_rules: int, n_checks: int, seed: int) -> list[dict]:
    """Seeded request mix: random mesh traffic + crafted rows that
    deterministically exercise the deny and whitelist rules (random
    traffic alone rarely matches the per-rule predicates)."""
    from istio_tpu.testing import workloads

    dicts = workloads.make_request_dicts(n_checks, seed=seed)
    n_srv = max(n_rules // 2, 1)
    for i in range(n_rules):
        dicts.append({
            "destination.service":
                f"svc{i % n_srv}.ns{i % 23}.svc.cluster.local",
            "source.namespace": f"ns{(i * 5) % 25}",
            "request.method": "GET",
            "request.path": "/api/v0/products/1",
            "request.host": f"x.ns{i % 23}.cluster.local",
            "connection.mtls": True,
            "request.headers": {"cookie": "session=0"},
        })
    return dicts


def main(n_rules: int = 24, n_checks: int = 32, seed: int = 3) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import prometheus_client

    from istio_tpu.adapters.prometheus_adapter import PrometheusHandler
    from istio_tpu.adapters.sdk import Env
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    store = workloads.make_store(n_rules, seed=seed)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=32, buckets=(8, 32),
        # exercise the background drain cadence too; final counts come
        # from an explicit drain at the end (cumulative either way)
        rulestats_drain_s=0.05,
        default_manifest=workloads.MESH_MANIFEST))
    # adapter-driven export: a real prometheus adapter handler is one
    # of the drain's consumers — its scrape must agree with the
    # aggregator at the end
    prom = PrometheusHandler(
        {"metrics": [
            {"name": "rulestats.hits", "kind": "COUNTER",
             "label_names": ["rule", "namespace"]},
            {"name": "rulestats.denies", "kind": "COUNTER",
             "label_names": ["rule", "namespace"]},
            {"name": "rulestats.errors", "kind": "COUNTER",
             "label_names": ["rule"]},
        ]}, Env("rulestats-smoke"))
    srv.rulestats.add_exporter(prom)
    intro = IntrospectServer(runtime=srv)
    g = MixerGrpcServer(runtime=srv)
    client = None
    native = None
    native_client = None
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 32))
        intro_port = intro.start()
        grpc_port = g.start()
        # verdict caching OFF: a client-cached verdict never reaches
        # the server, and the recount covers every sent request
        client = MixerClient(f"127.0.0.1:{grpc_port}",
                             enable_check_cache=False)
        dicts = make_traffic(n_rules, n_checks, seed)
        served: list[dict] = []
        for d in dicts:
            client.check(d)
            served.append(d)

        # native front (fail-soft: toolchain may be absent)
        native_note = "served"
        try:
            from istio_tpu.api.native_server import NativeMixerServer
            native = NativeMixerServer(srv, pumps=1)
            nport = native.start()
            native_client = MixerClient(f"127.0.0.1:{nport}",
                                        enable_check_cache=False)
            for d in dicts[: max(len(dicts) // 2, 1)]:
                native_client.check(d)
                served.append(d)
        except Exception as exc:
            native_note = f"skipped: {type(exc).__name__}: {exc}"
            print(f"rulestats smoke: native front {native_note}",
                  file=sys.stderr)

        # final drain + exact recount
        srv.rulestats.drain()
        got = srv.rulestats.counts()
        snap = srv.controller.dispatcher.snapshot
        names = [f"{r.namespace}/{r.name}" if r.namespace else r.name
                 for r in snap.rules]
        bags = [bag_from_mapping(d) for d in served]
        hits, denies, errors = oracle_recount(snap, plan, bags)
        for ridx, name in enumerate(names):
            gotr = got.get(name, {"hits": 0, "denies": 0, "errors": 0})
            want = (hits.get(ridx, 0), denies.get(ridx, 0),
                    errors.get(ridx, 0))
            have = (gotr["hits"], gotr["denies"], gotr["errors"])
            if have != want:
                failures.append(
                    f"count mismatch rule {name}: drained "
                    f"hit/deny/err {have} != oracle {want}")
        if not hits:
            failures.append("oracle recount saw zero hits — the "
                            "traffic mix no longer exercises rules")
        if not denies:
            failures.append("oracle recount saw zero denies — the "
                            "crafted deny rows no longer fire")

        # /debug/rulestats agreement + exemplar trace links
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro_port}/debug/rulestats?k=64",
                timeout=30) as r:
            view = json.loads(r.read().decode())
        by_rule = {t["rule"]: t for t in view.get("top", ())}
        for name, c in got.items():
            if not (c["hits"] or c["denies"] or c["errors"]):
                continue
            t = by_rule.get(name)
            if t is None:
                failures.append(f"/debug/rulestats missing hot rule "
                                f"{name}")
            elif (t["hits"], t["denies"], t["errors"]) != \
                    (c["hits"], c["denies"], c["errors"]):
                failures.append(
                    f"/debug/rulestats disagrees for {name}: view "
                    f"{t['hits']}/{t['denies']}/{t['errors']} vs "
                    f"aggregator {c['hits']}/{c['denies']}/"
                    f"{c['errors']}")
        never_names = {e["rule"] for e in view.get("never_hit", ())}
        for name, c in got.items():
            if c["hits"] and name in never_names:
                failures.append(f"{name} listed never-hit with "
                                f"{c['hits']} hits")
        deny_rules = [n for n, c in got.items() if c["denies"]]
        ex_rules = set(view.get("exemplar_rules", ()))
        if deny_rules and not ex_rules & set(deny_rules):
            failures.append("no decision exemplars for any denying "
                            f"rule (denied: {deny_rules})")
        for t in view.get("top", ()):
            for ex in t.get("exemplars", ()):
                if not ex.get("trace_id"):
                    failures.append(
                        f"exemplar for {t['rule']} carries no trace "
                        f"id — not joinable with /debug/traces")
                break

        # adapter agreement: the prometheus exporter's scrape must sum
        # to the aggregator's totals per rule
        text = prometheus_client.generate_latest(
            prom.registry).decode()
        adapter_hits: dict[str, float] = {}
        for line in text.splitlines():
            if line.startswith("istio_tpu_rulestats_hits_total{"):
                labels, value = line.rsplit(" ", 1)
                rule = labels.split('rule="', 1)[1].split('"', 1)[0]
                adapter_hits[rule] = adapter_hits.get(rule, 0.0) + \
                    float(value)
        for name, c in got.items():
            if c["hits"] and \
                    int(adapter_hits.get(name, 0)) != c["hits"]:
                failures.append(
                    f"prometheus adapter disagrees for {name}: "
                    f"{adapter_hits.get(name)} vs {c['hits']}")

        # counter families on the merged /metrics surface
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro_port}/metrics",
                timeout=30) as r:
            mtext = r.read().decode()
        for fam in ("mixer_rule_check_hits_total",
                    "mixer_rule_check_denies_total",
                    "mixer_rulestats_drains_total"):
            if fam not in mtext:
                failures.append(f"counter family absent from "
                                f"/metrics: {fam}")
    finally:
        if native_client is not None:
            native_client.close()
        if native is not None:
            native.stop()
        if client is not None:
            client.close()
        g.stop()
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"rulestats smoke ok: {len(served)} checks over "
              f"grpc+native, drained counts == oracle recount, "
              f"introspect + adapter agree (native: {native_note})")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=24)
    ap.add_argument("--checks", type=int, default=32)
    ap.add_argument("--seed", type=int, default=3)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.checks, args.seed))
