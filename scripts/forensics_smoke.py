"""Forensics smoke: the CI gate that the tail-latency forensics plane
answers "why was THAT request slow" end to end.

Boots the overlay serving stack behind the REAL python gRPC front and
the introspect HTTP surface, then FAILS (nonzero exit) unless:

  1. CLEAN TRAFFIC IS SILENT: with every request under the capture
     threshold the flight recorder captures ZERO exemplars (and the
     dropped-counter family exposes zero-shaped on /metrics);
  2. A CHAOS-WEDGED ADAPTER IS ATTRIBUTED: a wedged handler's slow
     requests produce exemplars whose stage timeline names the guilty
     stage (the per-handler host-action wait) AND whose event
     annotations carry the overlapping chaos/breaker event — "why
     slow" is one GET on /debug/slow;
  3. A CONFIG SWAP UNDER LOAD IS ATTRIBUTED: requests slowed by a
     live republish capture exemplars annotated with the publish/
     prewarm events that overlapped them;
  4. THE SURFACES AGREE over real HTTP: /debug/slow, /debug/events
     and /metrics report the same exemplar/event counts; slow
     exemplars deep-link into /debug/traces by trace id and the new
     ?min_ms= filter returns only spans at least that long;
  5. /debug/profile?seconds=1 produces a non-empty trace artifact
     (fail-soft where the jax profiler is unavailable — the endpoint
     must still answer with a typed payload) and /debug/threads
     names the serving threads with live stacks;
  6. the recorder's clean-traffic overhead is ≤ 2%
     (forensics_overhead_pct, recorder on vs off, min-of-3 windows).

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_forensics_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/forensics_smoke.py [--rules N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_METRICS = ("mixer_forensics_dropped_total",
                    "mixer_forensics_slow_exemplars_total",
                    "mixer_forensics_events_total")

WEDGED = "cilist.istio-system"
DEADLINE_MS = 600.0
WEDGE_THRESHOLD_MS = 250.0
SWAP_THRESHOLD_MS = 30.0
OVERHEAD_MAX_PCT = 2.0


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.load(r)


def _overlay_request(i: int, n_services: int) -> dict:
    """Request matching make_store(host_overlay_every=5) rule i (see
    executor_smoke — i % 5 == 2, k == 0 → the cilist handler)."""
    return {
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        "request.path": f"/api/v{i % 3}/items",
    }


def main(n_rules: int = 60, n_checks: int = 8) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import forensics, monitor
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.runtime.store import Event
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    CHAOS.reset()
    forensics.RECORDER.reset()
    n_services = max(n_rules // 2, 1)
    store = workloads.make_store(n_rules, host_overlay_every=5)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        default_check_deadline_ms=DEADLINE_MS,
        host_breaker_failures=2, host_breaker_reset_s=0.4,
        # clean phase first: a generous threshold proves silence
        # (phase 2 tightens it via RECORDER.configure)
        slow_threshold_ms=10_000.0,
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    g = MixerGrpcServer(runtime=srv)
    client = None
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 16))
        http_port = intro.start()
        grpc_port = g.start()
        client = MixerClient(f"127.0.0.1:{grpc_port}",
                             enable_check_cache=False)

        ci_rules = [i for i in range(2, n_rules, 5)
                    if (i // 5) % 3 == 0]
        if not ci_rules:
            failures.append("overlay workload lost its cilist rules")
            raise RuntimeError("bad workload")

        # ---- 1. clean traffic under threshold: ZERO exemplars ------
        forensics.RECORDER.reset()
        base = monitor.forensics_counters()
        for i in range(12):
            client.check(_overlay_request(3 * i + 1, n_services))
        fc = monitor.forensics_counters()
        if fc["slow_captured"] != base["slow_captured"]:
            failures.append(
                f"clean traffic captured "
                f"{fc['slow_captured'] - base['slow_captured']} "
                f"exemplars under a 10s threshold")
        slow = _get_json(http_port, "/debug/slow")
        if slow["retained"] != 0 or slow["slowest"]:
            failures.append(
                f"/debug/slow not empty after clean traffic: "
                f"retained={slow['retained']}")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics",
                timeout=30) as r:
            text = r.read().decode()
        for name in REQUIRED_METRICS:
            if name not in text:
                failures.append(f"metric absent from /metrics: "
                                f"{name}")
        for ring in ("slow", "events"):
            if f'mixer_forensics_dropped_total{{ring="{ring}"}}' \
                    not in text:
                failures.append(
                    f"dropped counter not zero-shaped for ring="
                    f"{ring}")

        # ---- 2. wedged adapter: guilty stage + overlapping event ---
        forensics.RECORDER.configure(
            threshold_ms=WEDGE_THRESHOLD_MS)
        wedge_base = monitor.forensics_counters()
        CHAOS.wedge_adapter(WEDGED)
        for k in range(n_checks):
            client.check(_overlay_request(
                ci_rules[k % len(ci_rules)], n_services))
        CHAOS.unwedge_adapter(WEDGED)
        fc = monitor.forensics_counters()
        if fc["slow_captured"] <= wedge_base["slow_captured"]:
            failures.append(
                "wedged-adapter requests captured no slow exemplars")
        slow = _get_json(http_port, "/debug/slow?k=32")
        wedged_ex = [e for e in slow["slowest"]
                     if str(e.get("top_stage", "")).startswith(
                         "host:" + WEDGED)]
        if not wedged_ex:
            failures.append(
                f"no exemplar names the wedged handler's host wait "
                f"as the guilty stage (top stages: "
                f"{sorted({str(e.get('top_stage')) for e in slow['slowest']})})")
        else:
            ex = wedged_ex[0]
            kinds = {ev["kind"] for ev in ex.get("events", ())}
            if not kinds & {"chaos_wedge", "breaker"}:
                failures.append(
                    f"wedged exemplar not annotated with the "
                    f"overlapping chaos/breaker event (saw {sorted(kinds)})")
            if ex["e2e_ms"] < WEDGE_THRESHOLD_MS:
                failures.append(
                    f"exemplar under its own threshold: {ex}")
            # ---- 4a. deep link into /debug/traces by trace id ------
            tid = ex.get("trace_id")
            if not tid:
                failures.append("wedged exemplar carries no trace id")
            else:
                tr = _get_json(http_port,
                               f"/debug/traces?trace={tid}")
                spans = tr.get("spans", [])
                if not spans:
                    failures.append(
                        f"trace deep link {tid} returned no spans")
                if any(s.get("traceId") != tid for s in spans):
                    failures.append("?trace= filter leaked foreign "
                                    "spans")
        ev = _get_json(http_port, "/debug/events?kind=chaos_wedge")
        if not ev["events"]:
            failures.append(
                "/debug/events missing the chaos_wedge event")
        ev = _get_json(http_port, "/debug/events?kind=breaker")
        if not any(e["detail"].get("name") == "handler:" + WEDGED
                   for e in ev["events"]):
            failures.append(
                "/debug/events missing the wedged lane's breaker "
                "transition")

        # ---- 4b. surfaces agree: /debug/slow vs /metrics -----------
        slow = _get_json(http_port, "/debug/slow")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics",
                timeout=30) as r:
            text = r.read().decode()
        wire_slow = None
        for line in text.splitlines():
            if line.startswith("mixer_forensics_slow_exemplars_total"):
                wire_slow = int(float(line.rsplit(" ", 1)[1]))
        if wire_slow != slow["counters"]["slow_captured"]:
            failures.append(
                f"/metrics ({wire_slow}) and /debug/slow "
                f"({slow['counters']['slow_captured']}) disagree on "
                f"captured exemplars")
        evs = _get_json(http_port, "/debug/events")
        if evs["counters"]["events_recorded"] < len(evs["events"]):
            failures.append("/debug/events counter below the "
                            "retained ring")

        # ---- 4c. ?min_ms= filter on /debug/traces ------------------
        tr = _get_json(http_port, "/debug/traces?min_ms=400")
        short = [s for s in tr.get("spans", [])
                 if s.get("duration", 0) < 400_000]
        if short:
            failures.append(f"?min_ms=400 returned {len(short)} "
                            f"shorter spans")
        if not tr.get("spans"):
            failures.append("?min_ms=400 lost the wedged-phase spans "
                            "(each waited ~500ms)")

        # ---- 3. config swap under load: publish/prewarm attributed -
        time.sleep(0.5)   # let the wedge recovery settle
        forensics.RECORDER.configure(threshold_ms=SWAP_THRESHOLD_MS)
        forensics.RECORDER.reset()
        rev0 = srv.controller.dispatcher.snapshot.revision
        stop = threading.Event()
        drive_errors: list = []

        def drive() -> None:
            i = 0
            while not stop.is_set():
                try:
                    client.check(_overlay_request(3 * i + 1,
                                                  n_services))
                except Exception as exc:   # swap must not drop RPCs
                    drive_errors.append(str(exc))
                    return
                i += 1

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        try:
            key = ("rule", "ns0", "rule0")
            spec = dict(store.get(key))
            spec["match"] = spec["match"].replace(
                '"locked0"', '"swapped-team"')
            store.apply_events([Event(key, spec)])
            deadline = time.time() + 120.0
            while time.time() < deadline:
                if srv.controller.dispatcher.snapshot.revision > rev0:
                    break
                time.sleep(0.05)
            else:
                failures.append("config swap never published")
            time.sleep(0.3)   # a few post-publish requests
        finally:
            stop.set()
            t.join(timeout=30)
        if drive_errors:
            failures.append(f"swap-window request failed: "
                            f"{drive_errors[0]}")
        swap_ex = None
        deadline = time.time() + 10.0
        while time.time() < deadline and swap_ex is None:
            slow = _get_json(http_port, "/debug/slow?k=64")
            for e in slow["slowest"]:
                kinds = {ev["kind"] for ev in e.get("events", ())}
                if kinds & {"config_publish", "prewarm",
                            "bank_rebuild"}:
                    swap_ex = e
                    break
            if swap_ex is None:
                time.sleep(0.25)
        if swap_ex is None:
            failures.append(
                "no slow exemplar annotated with the overlapping "
                "config_publish/prewarm event during the swap window")
        elif not swap_ex.get("top_stage"):
            failures.append(
                f"swap exemplar names no guilty stage: {swap_ex}")
        ev = _get_json(http_port, "/debug/events?kind=config_publish")
        if not ev["events"]:
            failures.append(
                "/debug/events missing the config_publish event")

        # ---- 5a. /debug/profile?seconds=1 --------------------------
        try:
            prof = _get_json(http_port, "/debug/profile?seconds=1")
            if not (prof.get("n_files", 0) >= 1
                    and prof.get("bytes_total", 0) > 0):
                failures.append(
                    f"profile capture produced an empty artifact: "
                    f"{prof}")
            print(f"forensics smoke: profile artifact "
                  f"{prof.get('n_files')} files / "
                  f"{prof.get('bytes_total')} bytes in "
                  f"{prof.get('dir')}")
        except urllib.error.HTTPError as exc:
            body = exc.read().decode("utf-8", "replace")
            soft = False
            try:
                soft = exc.code == 503 and \
                    json.loads(body).get("available") is False
            except Exception:
                soft = False
            if soft:
                # fail-soft contract: the profiler is genuinely
                # unavailable on this rig — the endpoint answered
                # with a typed payload, which is the gate
                print(f"forensics smoke: profiler unavailable "
                      f"(fail-soft): {body[:160]}")
            else:
                failures.append(f"/debug/profile errored: {exc.code} "
                                f"{body[:160]}")

        # ---- 5b. /debug/threads ------------------------------------
        th = _get_json(http_port, "/debug/threads")
        names = {t["name"] for t in th["threads"]}
        if not any(n.startswith("check-batcher") for n in names):
            failures.append(
                f"/debug/threads missing the check-batcher thread "
                f"({sorted(names)[:8]}...)")
        if any(not t["stack"] for t in th["threads"]):
            failures.append("/debug/threads returned empty stacks")

        # ---- 6. clean-traffic overhead, recorder on vs off ---------
        forensics.RECORDER.configure(threshold_ms=10_000.0)
        bags = workloads.make_bags(64)
        # calibrate the A/B window to ≥250ms of work: on a warm
        # process a check_many can run in ~2ms, and a 10ms window
        # measures scheduler noise, not the recorder (observed 7%
        # phantom overhead from exactly that)
        srv.check_many(bags)   # warm
        t0 = time.perf_counter()
        srv.check_many(bags)
        per_call = max(time.perf_counter() - t0, 1e-4)
        steps = max(4, int(0.25 / per_call))

        def window() -> float:
            t0 = time.perf_counter()
            for _s in range(steps):
                srv.check_many(bags)
            return steps * len(bags) / (time.perf_counter() - t0)

        # PAIRED on/off windows, MEDIAN of per-pair ratios, ORDER
        # ALTERNATED per pair: this box swings a few percent window
        # to window (the bench README's variance caveat), so a
        # single A-then-B subtraction — or even best-of — misreads
        # drift as recorder cost; a fixed within-pair order turns a
        # monotone warming trend into a systematic bias favoring
        # whichever side runs second. Alternating the order flips
        # that bias's sign pair to pair, and the median cancels it.
        ratios = []
        on = off = 0.0
        try:
            for i in range(7):
                first_on = i % 2 == 0
                forensics.RECORDER.configure(enabled=first_on)
                a = window()
                forensics.RECORDER.configure(enabled=not first_on)
                b = window()
                on, off = (a, b) if first_on else (b, a)
                ratios.append(off / on if on > 0 else 1.0)
        finally:
            forensics.RECORDER.configure(enabled=True)
        ordered = sorted(ratios)
        med = ordered[len(ordered) // 2]
        # the GATE reads the lower-quartile pair: window noise on
        # this box is ±1-2% (the bench variance caveat) and spreads
        # ratios both ways around the true cost, so the 2nd-smallest
        # of 7 pairs is a robust LOWER bound on real overhead — a
        # genuine >2% recorder cost lifts every pair and still
        # fails, while one or two noisy pairs cannot
        low = ordered[1]
        overhead = (low - 1.0) / low * 100.0 if low > 0 else 0.0
        med_pct = (med - 1.0) / med * 100.0 if med > 0 else 0.0
        if overhead > OVERHEAD_MAX_PCT:
            failures.append(
                f"forensics_overhead_pct {overhead:.2f} > "
                f"{OVERHEAD_MAX_PCT} (lower-quartile off/on "
                f"{low:.4f}, median {med:.4f}, over {len(ratios)} "
                f"alternated paired windows)")
        print(f"forensics smoke: forensics_overhead_pct="
              f"{overhead:.2f} (lower-quartile {low:.4f}, median "
              f"{med_pct:.2f}%, last pair on={on:.0f}/s "
              f"off={off:.0f}/s)")
    finally:
        CHAOS.reset()
        forensics.RECORDER.configure(enabled=True, threshold_ms=0.0,
                                     adaptive=False)
        if client is not None:
            client.close()
        g.stop()
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print("forensics smoke ok: clean traffic silent, wedge and "
              "swap exemplars name their guilty stage + overlapping "
              "event, /debug/slow+/debug/events+/metrics agree, "
              "trace deep links + ?min_ms= filter work, profile/"
              "threads endpoints serve, overhead under the 2% gate")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=60)
    ap.add_argument("--checks", type=int, default=8)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.checks))
