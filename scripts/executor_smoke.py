"""Executor smoke: the CI gate that the adapter-executor plane
actually isolates, bounds and accounts host adapter work.

Boots the overlay serving stack (make_store(host_overlay_every) — the
genuinely-unfusable list shapes) behind the REAL python gRPC front
with a server-side default check deadline, wedges ONE adapter at the
chaos seam, and FAILS (nonzero exit) unless:

  1. ZERO requests exceed their deadline: every RPC against the
     wedged handler's rules answers within the deadline budget (the
     wedged backend holds its lane's workers, never the batch fold);
  2. degradation is TYPED AND COUNTED: wedged-rule responses carry
     the fail-closed UNAVAILABLE verdict, the executor's conservation
     ledger stays EXACT (submitted == sum of typed outcomes, overruns
     and breaker short-circuits visible), and rules on OTHER handlers
     keep their clean verdicts at full speed (bulkhead);
  3. /debug/executor agrees over real HTTP: lane state (breaker open
     on the wedged lane), the same conservation counters, and the
     maintenance/provider freshness view; the mixer_host_action_*
     families expose on /metrics;
  4. the lane breaker recovers by half-open probe once the wedge
     clears, and verdicts return to the clean baseline;
  5. the OPA scenario holds oracle parity: make_opa_store traffic
     (real Rego allow AND deny verdicts through the executor's opa
     lane) matches the generic host-oracle path status-for-status.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_executor_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/executor_smoke.py [--rules N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_METRICS = ("mixer_host_actions_total",
                    "mixer_host_actions_submitted_total",
                    "mixer_host_action_seconds",
                    "mixer_list_provider_refresh_total",
                    "mixer_list_provider_refresh_failures")

WEDGED = "cilist.istio-system"
DEADLINE_MS = 400.0


def _overlay_request(i: int, n_services: int) -> dict:
    """Request matching make_store(host_overlay_every=5) rule i
    (i % 5 == 2: k=(i//5)%3 → 0 cilist / 1 provlist / 2 dynpat)."""
    return {
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        # k==7 rules gate on request.path.startsWith("/api/v{i%3}/")
        # — the path must satisfy it or the rule (and its overlay
        # action) never fires and the smoke measures nothing
        "request.path": f"/api/v{i % 3}/items",
    }


def main(n_rules: int = 60, n_checks: int = 24) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import monitor
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    failures: list[str] = []
    CHAOS.reset()
    n_services = max(n_rules // 2, 1)
    store = workloads.make_store(n_rules, host_overlay_every=5)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        default_check_deadline_ms=DEADLINE_MS,
        host_breaker_failures=2, host_breaker_reset_s=0.4,
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    g = MixerGrpcServer(runtime=srv)
    client = None
    base = monitor.host_action_counters()
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((8, 16))
        http_port = intro.start()
        grpc_port = g.start()
        client = MixerClient(f"127.0.0.1:{grpc_port}",
                             enable_check_cache=False)

        # overlay rules by handler kind (i%5==2; k=(i//5)%3)
        ci_rules = [i for i in range(2, n_rules, 5)
                    if (i // 5) % 3 == 0]
        prov_rules = [i for i in range(2, n_rules, 5)
                      if (i // 5) % 3 == 1]
        if not ci_rules or not prov_rules:
            failures.append("overlay workload lost its handler mix")
            raise RuntimeError("bad workload")
        ci_req = _overlay_request(ci_rules[0], n_services)
        prov_req = _overlay_request(prov_rules[0], n_services)

        # clean verdicts over the wire = the conformance baseline
        clean_ci = client.check(ci_req).precondition.status.code
        clean_prov = client.check(prov_req).precondition.status.code

        # ---- wedge ONE adapter; drive closed-loop load -------------
        CHAOS.wedge_adapter(WEDGED)
        budget_s = DEADLINE_MS / 1e3 + 0.35   # deadline + wire slack
        wedged_codes = []
        for k in range(n_checks):
            t0 = time.perf_counter()
            resp = client.check(_overlay_request(
                ci_rules[k % len(ci_rules)], n_services))
            wall = time.perf_counter() - t0
            wedged_codes.append(resp.precondition.status.code)
            if wall > budget_s:
                failures.append(
                    f"request {k} against the wedged handler took "
                    f"{wall * 1e3:.0f}ms > {budget_s * 1e3:.0f}ms "
                    f"budget — a wedged adapter held the batch")
        # typed degradation: fail-closed UNAVAILABLE (14), never OK,
        # never a hang converted to INTERNAL
        bad = [c for c in wedged_codes if c != 14]
        if bad:
            failures.append(
                f"wedged-rule verdicts not typed UNAVAILABLE: "
                f"{sorted(set(bad))}")
        # bulkhead: the OTHER handler's rules still answer their
        # clean verdict, fast
        t0 = time.perf_counter()
        code = client.check(prov_req).precondition.status.code
        prov_wall = time.perf_counter() - t0
        if code != clean_prov:
            failures.append(
                f"bulkhead broken: provlist verdict flipped "
                f"{clean_prov} -> {code} while cilist was wedged")
        if prov_wall > budget_s:
            failures.append(
                f"bulkhead broken: provlist request took "
                f"{prov_wall * 1e3:.0f}ms behind the wedged lane")
        hc = monitor.host_action_counters()
        d_outcomes = {k: hc["outcomes"][k] - base["outcomes"][k]
                      for k in hc["outcomes"]}
        if d_outcomes["overrun"] < 2:
            failures.append(
                f"overruns not counted: {d_outcomes}")
        if d_outcomes["breaker_open"] < 1:
            failures.append(
                f"open lane breaker never short-circuited: "
                f"{d_outcomes}")
        if not hc["exact"]:
            failures.append(
                f"host-action conservation broken: submitted="
                f"{hc['submitted']} resolved={hc['resolved']}")

        # ---- /debug/executor + /metrics agree over real HTTP -------
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/debug/executor",
                timeout=10) as r:
            dbg = json.load(r)
        if not dbg.get("enabled"):
            failures.append("/debug/executor reports disabled")
        lane = dbg.get("lanes", {}).get(WEDGED)
        if lane is None:
            failures.append(f"/debug/executor missing lane {WEDGED}")
        elif lane["breaker"]["state"] != "open":
            failures.append(
                f"/debug/executor breaker state "
                f"{lane['breaker']['state']!r}, expected open")
        cs = dbg.get("counters", {})
        if cs.get("submitted") != hc["submitted"]:
            failures.append("/debug/executor counters disagree with "
                            "the in-process ledger")
        provs = dbg.get("providers", {})
        if "provlist.istio-system" not in provs:
            failures.append("/debug/executor missing the provider "
                            "freshness view")
        if WEDGED not in dbg.get("chaos", {}).get("adapter_wedged",
                                                  ()):
            failures.append("/debug/executor chaos pane missing the "
                            "armed wedge")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics",
                timeout=10) as r:
            text = r.read().decode()
        for name in REQUIRED_METRICS:
            if name not in text:
                failures.append(f"metric absent from /metrics: "
                                f"{name}")

        # ---- recovery: unwedge → half-open probe → clean verdict ---
        CHAOS.unwedge_adapter(WEDGED)
        time.sleep(0.45)
        code = client.check(ci_req).precondition.status.code
        if code != clean_ci:
            failures.append(
                f"post-recovery verdict diverged: {code} != "
                f"{clean_ci}")
        if srv.executor.lane(WEDGED).breaker.state != "closed":
            failures.append(
                f"lane breaker did not recover (state="
                f"{srv.executor.lane(WEDGED).breaker.state})")

        # ---- OPA scenario parity gate (in-process) -----------------
        opa_store = workloads.make_opa_store(42)
        opa_srv = RuntimeServer(opa_store, ServerArgs(
            batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
            default_manifest=workloads.MESH_MANIFEST))
        try:
            bags = [bag_from_mapping(x)
                    for x in workloads.make_opa_requests(24, 42)]
            d = opa_srv.controller.dispatcher
            fused = [r.status_code for r in d.check(bags)]
            oracle = [r.status_code
                      for r in d.check_host_oracle(bags)]
            if fused != oracle:
                failures.append(
                    f"OPA executor-path verdicts diverged from the "
                    f"host oracle: "
                    f"{sum(a != b for a, b in zip(fused, oracle))}/"
                    f"{len(bags)} rows")
            if 7 not in fused or 0 not in fused:
                failures.append(
                    f"OPA corpus lost its allow/deny mix: "
                    f"{sorted(set(fused))}")
        finally:
            opa_srv.close()
    finally:
        CHAOS.reset()
        if client is not None:
            client.close()
        g.stop()
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"executor smoke ok: {n_checks} wedged-handler RPCs all "
              f"answered inside the {DEADLINE_MS:.0f}ms deadline with "
              f"typed UNAVAILABLE, bulkhead held, conservation exact, "
              f"/debug/executor+metrics agree, breaker recovered, OPA "
              f"parity on 24 rows")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=60)
    ap.add_argument("--checks", type=int, default=24)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.checks))
