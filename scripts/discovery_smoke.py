"""Discovery smoke: boot the snapshot-served Pilot discovery plane
over a REAL HTTP front with a Zipf fleet world, and FAIL (nonzero
exit) unless

  1. every sidecar's SDS/CDS/RDS/LDS pull serves 200 with parseable
     JSON, and a sampled node set is BYTE-EXACT against the unscoped
     single-node generation path (legacy per-node builders over the
     live registry/config store — no snapshot, no cache, no grouping,
     no batched admission);
  2. a one-namespace churn invalidates ONLY the scoped node groups:
     the churned namespace's RDS re-pull is a miss with changed bytes
     (still parity-exact), an unrelated namespace's RDS re-pull is a
     HIT on a carried entry, and its SDS entry stays live;
  3. delta push is scoped: a watcher parked on the churned
     namespace's shard wakes with the new generation while a watcher
     on a different shard times out unchanged (no full-fleet
     re-pull);
  4. /debug/discovery (on the introspect server AND the discovery
     front) agrees with the smoke's own accounting — generation,
     cache entries, hit/miss/carried/invalidated deltas, push
     fan-out observations, non-empty serve/generate stages;
  5. draining is typed: after begin_drain() new pulls answer 503
     UNAVAILABLE (grpc code 14), parked watchers release, and a
     stop/start cycle serves again.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_discovery_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/discovery_smoke.py \
           [--services N] [--namespaces N] [--replicas N] [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _get(port: int, path: str, timeout: float = 30.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return r.status, r.read()


def main(n_services: int = 48, n_namespaces: int = 8,
         replicas: int = 3, seed: int = 7) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.pilot.discovery import DiscoveryService
    from istio_tpu.testing import workloads

    failures: list[str] = []
    ds = None
    intro = None
    try:
        registry, store, nodes, meta = workloads.make_discovery_world(
            n_services=n_services, n_namespaces=n_namespaces,
            replicas=replicas, source_ns=2, seed=seed)
        ds = DiscoveryService(registry, store)
        port = ds.start()
        intro = IntrospectServer(discovery=ds)
        intro_port = intro.start()

        def node_port(node: str) -> int:
            return 8000 + meta["ns_of"][nodes.index(node) // replicas]

        # -- 1. full fleet pull over real HTTP + parity sample -------
        served = 0
        for n in nodes:
            p = node_port(n)
            for path in (f"/v1/routes/{p}/istio/{n}",
                         f"/v1/clusters/istio/{n}",
                         f"/v1/listeners/istio/{n}"):
                code, body = _get(port, path)
                if code != 200:
                    failures.append(f"{path}: HTTP {code}")
                    break
                json.loads(body)
                served += 1
        for i in range(0, n_services, max(n_services // 8, 1)):
            k = meta["ns_of"][i]
            code, body = _get(
                port, f"/v1/registration/svc{i}.ns{k}"
                      f".svc.cluster.local|http")
            if code != 200 or not json.loads(body)["hosts"]:
                failures.append(f"sds svc{i}: bad response")
        sample = nodes[:: max(len(nodes) // 8, 1)][:8]
        for n in sample:
            p = node_port(n)
            for path in (f"/v1/routes/{p}/istio/{n}",
                         f"/v1/clusters/istio/{n}",
                         f"/v1/listeners/istio/{n}"):
                _, got = _get(port, path)
                want = ds.reference_bytes(path)
                if got != want:
                    failures.append(
                        f"parity: {path} differs from the unscoped "
                        f"single-node path")

        # -- 2. one-namespace churn: scoped invalidation -------------
        churn_k = max(meta["rules_by_ns"])
        victims = [k for k in sorted(meta["rules_by_ns"])
                   if k != churn_k and k >= meta["source_ns"]]
        victim_k = victims[-1] if victims else None
        if victim_k is None:
            failures.append("world has no unrelated namespace with "
                            "rules — cannot judge scoped invalidation")
            raise RuntimeError("bad world")
        churn_node = meta["nodes_by_ns"][churn_k][0]
        victim_node = meta["nodes_by_ns"][victim_k][0]
        _, churn_before = _get(
            port, f"/v1/routes/{8000 + churn_k}/istio/{churn_node}")
        gen_before = ds.generation
        stats_before = ds._cache.stats()

        # watchers park BEFORE the churn (scoped delta push)
        snap = ds.snapshot
        churn_shard = snap.plan.shard_of(f"ns{churn_k}")
        other = None
        for k, ns_nodes in sorted(meta["nodes_by_ns"].items()):
            if snap.plan.shard_of(f"ns{k}") != churn_shard:
                other = ns_nodes[0]
                break
        watch_out: dict = {}

        def watch(tag: str, node: str, timeout: float) -> None:
            _, body = _get(
                port, f"/v1/watch/istio/{node}?version={gen_before}"
                      f"&timeout={timeout}", timeout=timeout + 10)
            watch_out[tag] = json.loads(body)

        t_in = threading.Thread(target=watch,
                                args=("scoped", churn_node, 10.0))
        t_out = threading.Thread(target=watch,
                                 args=("other", other, 1.5))
        t_in.start()
        t_out.start()
        time.sleep(0.3)
        workloads.churn_discovery_rule(store, meta, churn_k, 1)
        t_in.join()
        t_out.join()
        if ds.generation != gen_before + 1:
            failures.append(f"churn publish: generation "
                            f"{ds.generation} != {gen_before + 1}")
        if not watch_out.get("scoped", {}).get("changed"):
            failures.append(f"scoped watcher did not wake: "
                            f"{watch_out.get('scoped')}")
        if watch_out.get("other", {}).get("changed"):
            failures.append(f"out-of-scope watcher woke on an "
                            f"unrelated churn: {watch_out.get('other')}")

        # unrelated RDS re-pull: HIT on a carried entry
        h0 = ds._cache.stats()
        _get(port, f"/v1/routes/{8000 + victim_k}/istio/{victim_node}")
        h1 = ds._cache.stats()
        if h1["hits"] - h0["hits"] != 1 or h1["misses"] != h0["misses"]:
            failures.append(
                f"one-namespace churn did not leave the unrelated "
                f"ns{victim_k} RDS entry live (hits +"
                f"{h1['hits'] - h0['hits']}, misses +"
                f"{h1['misses'] - h0['misses']})")
        # unrelated SDS entry stays live too
        vs = meta["hosts_by_ns"][victim_k][0]
        _get(port, f"/v1/registration/{vs}|http")
        h2 = ds._cache.stats()
        s0 = h2["misses"]
        _get(port, f"/v1/registration/{vs}|http")
        h3 = ds._cache.stats()
        if h3["misses"] != s0:
            failures.append("unrelated SDS entry not served from "
                            "cache after churn")
        # churned RDS: new bytes, still parity-exact
        path = f"/v1/routes/{8000 + churn_k}/istio/{churn_node}"
        _, churn_after = _get(port, path)
        if churn_after == churn_before:
            failures.append("churned namespace's RDS bytes unchanged "
                            "after a route-rule update")
        if churn_after != ds.reference_bytes(path):
            failures.append("post-churn RDS differs from the "
                            "unscoped single-node path")
        stats_after = ds._cache.stats()
        if stats_after["carried"] <= stats_before["carried"]:
            failures.append("publish sweep carried no entries — "
                            "invalidation is not scoped")

        # -- 4. /debug/discovery agreement ---------------------------
        for where, dbg_port in (("front", port),
                                ("introspect", intro_port)):
            _, body = _get(dbg_port, "/debug/discovery")
            view = json.loads(body)
            if where == "introspect" and not view.get("enabled"):
                failures.append("/debug/discovery disabled on the "
                                "introspect server")
                continue
            if view["generation"] != ds.generation:
                failures.append(f"/debug/discovery ({where}) "
                                f"generation {view['generation']} != "
                                f"{ds.generation}")
            cache = view["cache"]
            live = ds._cache.stats()
            for key in ("entries", "hits", "misses", "carried",
                        "invalidated"):
                if abs(cache[key] - live[key]) > 2:   # concurrent GETs
                    failures.append(
                        f"/debug/discovery ({where}) cache.{key} "
                        f"{cache[key]} != live {live[key]}")
            if not view["push"].get("count"):
                failures.append(f"/debug/discovery ({where}) has no "
                                f"push fan-out observations after a "
                                f"watched churn")
            for stage in ("serve", "generate", "snapshot_build",
                          "invalidate"):
                if not view["stages"].get(stage, {}).get("count"):
                    failures.append(
                        f"/debug/discovery ({where}) stage "
                        f"{stage!r} has no observations")

        # -- 5. typed draining + restart cycle -----------------------
        ds.begin_drain()
        try:
            _get(port, f"/v1/routes/{8000 + churn_k}/istio/"
                       f"{churn_node}")
            failures.append("draining server served a config pull")
        except urllib.error.HTTPError as exc:
            body = json.loads(exc.read())
            if exc.code != 503 or body.get("code") != "UNAVAILABLE" \
                    or body.get("grpc_code") != 14:
                failures.append(f"draining rejection untyped: "
                                f"{exc.code} {body}")
        ds.stop()
        port2 = ds.start()
        code, _body = _get(port2, f"/v1/clusters/istio/{nodes[0]}")
        if code != 200:
            failures.append(f"restart cycle: HTTP {code}")
    finally:
        if intro is not None:
            intro.close()
        if ds is not None:
            ds.stop()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"discovery smoke ok: {meta['n_sidecars']} sidecars / "
              f"{n_services} services / {n_namespaces} ns, "
              f"{served} HTTP serves, parity exact "
              f"({len(sample)}-node sample, pre+post churn), "
              f"one-ns churn scoped (gen {ds.generation}), "
              f"push fan-out scoped, typed drain + restart ok")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--services", type=int, default=48)
    ap.add_argument("--namespaces", type=int, default=8)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    sys.exit(main(args.services, args.namespaces, args.replicas,
                  args.seed))
