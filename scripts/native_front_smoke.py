"""Smoke-drive the native C++ front-end end-to-end on CPU:
RuntimeServer + NativeMixerServer, one grpcio-client check, then the
C++ h2load client against the same server (payload-file plumbing and
JSON output). Safe to run anywhere (hermetic CPU jax)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json          # noqa: E402


def main() -> None:
    from istio_tpu.api import MixerClient
    from istio_tpu.api.native_server import NativeMixerServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import perf, workloads

    srv = RuntimeServer(workloads.make_store(200), ServerArgs(
        batch_window_s=0.001, max_batch=256, buckets=(256,),
        default_manifest=workloads.MESH_MANIFEST))
    native = NativeMixerServer(srv, min_fill=32, window_us=1000)
    port = native.start()
    try:
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        r = client.check(workloads.make_request_dicts(1)[0])
        print("grpcio check status:", r.precondition.status.code)
        client.close()

        payloads = perf.make_check_payloads(
            workloads.make_request_dicts(64))
        rep = perf.run_h2load(port, payloads, 500, 64, 0.5)
        print("h2load:", json.dumps(rep))
        assert rep["errors"] == 0, rep
        print("counters:", json.dumps(native.counters()))
    finally:
        native.stop()
        srv.close()
    print("SMOKE OK")


if __name__ == "__main__":
    main()
