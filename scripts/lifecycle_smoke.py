"""Lifecycle smoke: the CI gate that the serving stack is RESTARTABLE.

PR 7's hard constraint — the stack must survive restart storms, SIGTERM
mid-traffic and config swaps under load with ZERO aborts/core dumps and
ZERO silently dropped in-flight requests. Three phases, each failing
(nonzero exit) unless the lifecycle plane degrades exactly as designed:

  (a) RESTART STORM — N× native C++ front start/stop cycles over one
      RuntimeServer, live gRPC traffic on a sampling of cycles, a
      DELIBERATE double-stop every cycle (the C++ live-handle registry
      must make it a no-op, never a use-after-free), and per-cycle wire
      accounting: in_flight must drain to zero and every decoded
      request must have a response written (no silent drops).
  (b) SIGTERM UNDER LIVE TRAFFIC — a child process serves the native
      front while this process drives closed-loop checks; SIGTERM
      mid-traffic runs the ordered shutdown (h2srv_quiesce → drain →
      pump join → h2srv_stop → RuntimeServer.shutdown) and the child
      must exit 0 — a negative returncode means SIGABRT/SIGSEGV, the
      crash-on-teardown class this PR exists to kill. The child's
      final counters must show in_flight == 0.
  (c) SWAP STORM — rapid config churn under concurrent check streams:
      serving never pauses (every check answers or raises a typed
      rejection), no exception escapes, and the LAST config wins. The
      served-shape pre-swap warm + background warm + host-oracle
      bridge (runtime/controller.py, Dispatcher._check_fused) make the
      storm cheap by construction.

Runnable anywhere under JAX_PLATFORMS=cpu; tier-1 invokes main()
in-process (tests/test_lifecycle_smoke.py, the chaos_smoke pattern).

Usage: JAX_PLATFORMS=cpu python scripts/lifecycle_smoke.py
           [--cycles N] [--swaps N] [--traffic-s S]
       (internal: --sigterm-child runs the phase-b server process)
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

OK, PERMISSION_DENIED, UNAVAILABLE = 0, 7, 14


def _smoke_store():
    """Tiny deterministic config: one deny rule + one allow path —
    cheap to compile (restart cycles must be wire-dominated, not
    XLA-dominated) but still exercising the fused device path."""
    from istio_tpu.runtime import MemStore

    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "denyadmin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    return s


def _runtime():
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import RuntimeServer, ServerArgs

    srv = RuntimeServer(_smoke_store(), ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(8,),
        initial_prewarm=False, rulestats_drain_s=0))
    # compile the serving shape BEFORE the storm: the cycles measure
    # lifecycle hygiene, not first-compile latency
    srv.check(bag_from_mapping({"request.path": "/warm"}))
    return srv


def _grpc_available() -> bool:
    try:
        import grpc  # noqa: F401
        return True
    except Exception:
        return False


# ------------------------------------------------------- (a) restarts

def restart_storm(failures: list, cycles: int) -> None:
    from istio_tpu.api.native_server import NativeMixerServer

    srv = _runtime()
    use_grpc = _grpc_available()
    try:
        for cycle in range(cycles):
            native = NativeMixerServer(srv, min_fill=1, window_us=200,
                                       pumps=2)
            port = native.start()
            if use_grpc and cycle % 10 == 0:
                from istio_tpu.api.client import MixerClient
                cli = MixerClient(f"127.0.0.1:{port}",
                                  enable_check_cache=False)
                try:
                    r1 = cli.check({"request.path": "/admin/x"})
                    r2 = cli.check({"request.path": "/ok"})
                    if r1.precondition.status.code != PERMISSION_DENIED \
                            or r2.precondition.status.code != OK:
                        failures.append(
                            f"cycle {cycle}: wrong verdicts "
                            f"({r1.precondition.status.code}, "
                            f"{r2.precondition.status.code})")
                finally:
                    cli.close()
            native.stop(grace=5.0)
            c = native.counters()
            if c.get("in_flight", 0) != 0:
                failures.append(
                    f"cycle {cycle}: {c['in_flight']} requests "
                    f"enqueued but never answered (silent drop)")
            if c.get("responses_sent", 0) < c.get("requests_decoded", 0):
                failures.append(
                    f"cycle {cycle}: decoded "
                    f"{c['requests_decoded']} > sent "
                    f"{c['responses_sent']} (silent drop)")
            # double-stop: the C++ registry guard must no-op this
            # (before PR 7 it was a use-after-free → abort)
            native.stop()
    finally:
        srv.close()


# -------------------------------------------------------- (b) SIGTERM

def sigterm_child() -> int:
    """The phase-b server process: serve the native front until
    SIGTERM, then run the ordered graceful shutdown and report the
    final wire accounting on stdout."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.api.native_server import NativeMixerServer

    srv = _runtime()
    native = NativeMixerServer(srv, min_fill=1, window_us=300, pumps=2)
    port = native.start()
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    print(f"PORT {port}", flush=True)
    done.wait()
    # ordered shutdown UNDER live traffic: quiesce intake (typed
    # UNAVAILABLE for new wire requests), drain in-flight rows, join
    # pumps, tear down the wire, then drain the runtime itself
    native.stop(grace=5.0)
    counters = native.counters()
    srv.shutdown(deadline=5.0)
    print("COUNTERS " + json.dumps(counters), flush=True)
    if counters.get("in_flight", 0) != 0:
        return 3   # enqueued rows vanished: silent drop
    return 0


def sigterm_under_load(failures: list, traffic_s: float) -> None:
    if not _grpc_available():
        print("lifecycle_smoke: grpc unavailable — SIGTERM phase "
              "runs without client traffic")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--sigterm-child"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    port = None
    lines: list = []
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            if line.startswith("PORT "):
                port = int(line.split()[1])
                break
        if port is None:
            failures.append("sigterm child never reported a port")
            proc.kill()
            return
        # drain the rest of the child's stdout on a thread so the
        # pipe never fills and blocks the child's shutdown prints
        reader = threading.Thread(
            target=lambda: lines.extend(proc.stdout),
            daemon=True)
        reader.start()

        served = [0]
        rejected = [0]
        client_bugs: list = []
        stop = threading.Event()

        def drive(tid: int) -> None:
            if not _grpc_available():
                return
            import grpc
            from istio_tpu.api.client import MixerClient
            cli = MixerClient(f"127.0.0.1:{port}",
                              enable_check_cache=False)
            i = 0
            try:
                while not stop.is_set():
                    try:
                        r = cli.check(
                            {"request.path": f"/t{tid}/{i}"})
                        if r.precondition.status.code in (
                                OK, UNAVAILABLE):
                            served[0] += 1
                        else:
                            client_bugs.append(
                                r.precondition.status.code)
                    except grpc.RpcError:
                        # typed rejection / connection close during
                        # the drain — the client SAW an outcome,
                        # nothing hung and nothing silently vanished
                        rejected[0] += 1
                        if stop.is_set():
                            break
                    i += 1
            finally:
                cli.close()

        threads = [threading.Thread(target=drive, args=(t,),
                                    daemon=True) for t in range(3)]
        for t in threads:
            t.start()
        time.sleep(traffic_s)
        proc.send_signal(signal.SIGTERM)
        try:
            rc = proc.wait(timeout=90)
        except subprocess.TimeoutExpired:
            proc.kill()
            failures.append("sigterm child hung past 90s — graceful "
                            "shutdown wedged")
            rc = None
        stop.set()
        for t in threads:
            t.join(timeout=30)
            if t.is_alive():
                failures.append("client thread hung across the "
                                "shutdown (a request never resolved)")
        if rc is not None and rc != 0:
            kind = "killed by signal (abort/core dump)" if rc < 0 \
                else "nonzero exit"
            failures.append(
                f"sigterm child rc={rc} ({kind}); output tail: "
                f"{''.join(lines)[-2000:]}")
        if _grpc_available() and served[0] == 0:
            failures.append("no request served before SIGTERM — the "
                            "under-load premise never held")
        for line in lines:
            if line.startswith("COUNTERS "):
                c = json.loads(line[len("COUNTERS "):])
                if c.get("in_flight", 0) != 0:
                    failures.append(
                        f"child wire counters leak in_flight="
                        f"{c['in_flight']} (silent drops)")
                break
        else:
            if rc == 0:
                failures.append("child exited 0 but never printed "
                                "its final counters")
    finally:
        if proc.poll() is None:
            proc.kill()


# ----------------------------------------------------- (c) swap storm

def swap_storm(failures: list, swaps: int) -> None:
    from istio_tpu.attribute.bag import bag_from_mapping

    srv = _runtime()
    store = srv.controller.store
    errors: list = []
    answered = [0]
    stop = threading.Event()

    def stream(tid: int) -> None:
        i = 0
        while not stop.is_set():
            try:
                r = srv.check(bag_from_mapping(
                    {"request.path": f"/s{tid}/{i}"}))
                if r.status_code not in (OK, PERMISSION_DENIED):
                    errors.append(("status", r.status_code))
                answered[0] += 1
            except Exception as exc:   # typed rejections only
                from istio_tpu.runtime.resilience import CheckRejected
                if not isinstance(exc, CheckRejected):
                    errors.append(("raise", repr(exc)))
            i += 1

    threads = [threading.Thread(target=stream, args=(t,), daemon=True)
               for t in range(2)]
    try:
        for t in threads:
            t.start()
        for i in range(swaps):
            store.set(("rule", "istio-system", f"storm{i}"), {
                "match": f'request.path.startsWith("/storm{i}/")',
                "actions": [{"handler": "denyall",
                             "instances": ["nothing"]}]})
            time.sleep(0.05)
        # the storm's LAST rule must take effect (every intermediate
        # swap may be debounce-coalesced — only the final config is
        # contractual)
        probe = bag_from_mapping(
            {"request.path": f"/storm{swaps - 1}/x"})
        deadline = time.time() + 60
        while time.time() < deadline:
            if srv.check(probe).status_code == PERMISSION_DENIED:
                break
            time.sleep(0.05)
        else:
            failures.append("swap storm: final config never took "
                            "effect within 60s")
        stop.set()
        for t in threads:
            t.join(timeout=15)
            if t.is_alive():
                failures.append("swap storm: stream thread hung")
        if errors:
            failures.append(f"swap storm: {len(errors)} bad outcomes, "
                            f"first: {errors[0]}")
        if not answered[0]:
            failures.append("swap storm: nothing served during churn")
    finally:
        stop.set()
        srv.close()


def main(cycles: int = 50, swaps: int = 6,
         traffic_s: float = 1.0) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failures: list = []

    t0 = time.time()
    restart_storm(failures, cycles)
    t1 = time.time()
    print(f"lifecycle_smoke: restart storm ({cycles} cycles) "
          f"{t1 - t0:.1f}s, {len(failures)} failure(s)")
    sigterm_under_load(failures, traffic_s)
    t2 = time.time()
    print(f"lifecycle_smoke: sigterm-under-load {t2 - t1:.1f}s, "
          f"{len(failures)} cumulative failure(s)")
    swap_storm(failures, swaps)
    print(f"lifecycle_smoke: swap storm ({swaps} swaps) "
          f"{time.time() - t2:.1f}s, {len(failures)} cumulative "
          f"failure(s)")

    for f in failures:
        print(f"lifecycle_smoke FAIL: {f}")
    if not failures:
        print("lifecycle_smoke: OK (zero aborts, zero dropped "
              "in-flight requests)")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=50)
    ap.add_argument("--swaps", type=int, default=6)
    ap.add_argument("--traffic-s", type=float, default=1.0)
    ap.add_argument("--sigterm-child", action="store_true",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.sigterm_child:
        sys.exit(sigterm_child())
    sys.exit(main(cycles=args.cycles, swaps=args.swaps,
                  traffic_s=args.traffic_s))
