"""Introspect smoke: boot a small ruleset, fire checks, scrape
/metrics over real HTTP, and FAIL (nonzero exit) if the live p99
gauge or any serving stage histogram is absent from the exposition.

The observability contract this pins: every future perf/robustness PR
can prove its hot-path effect from a live scrape — if the stage
decomposition ever silently stops populating, CI catches it here, not
three perf rounds later. Runnable under JAX_PLATFORMS=cpu; tier-1
invokes main() in-process (tests/test_introspect_smoke.py).

Usage: JAX_PLATFORMS=cpu python scripts/introspect_smoke.py \
           [--rules N] [--checks N]
"""
import argparse
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_STAGES = ("queue_wait", "tensorize", "h2d", "device_step",
                   "fold", "respond")
REQUIRED_GAUGES = ("mixer_check_p99_ms", "check_p99_under_target")


def main(n_rules: int = 32, n_checks: int = 100) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    store = workloads.make_store(n_rules)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=64, buckets=(16, 64),
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    failures: list[str] = []
    try:
        plan = srv.controller.dispatcher.fused
        if plan is not None:
            plan.prewarm((16, 64))
        port = intro.start()
        bags = workloads.make_bags(max(n_checks, 1))
        # half through the pre-batched entry, half through the batcher
        # — both serving entries must feed the decomposition
        srv.check_many(bags[: len(bags) // 2])
        for bag in bags[len(bags) // 2:]:
            srv.check(bag)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()

        for stage in REQUIRED_STAGES:
            needle = f'stage="{stage}"'
            count_ok = any(
                line.startswith("mixer_check_stage_seconds_count")
                and needle in line
                and float(line.rsplit(" ", 1)[1]) > 0
                for line in text.splitlines())
            if not count_ok:
                failures.append(
                    f"stage histogram absent/empty: {stage}")
        for gauge in REQUIRED_GAUGES:
            if not any(line.startswith(gauge)
                       for line in text.splitlines()):
                failures.append(f"gauge absent: {gauge}")
        p99_lines = [line for line in text.splitlines()
                     if line.startswith("mixer_check_p99_ms ")]
        if p99_lines and float(p99_lines[0].rsplit(" ", 1)[1]) <= 0:
            failures.append("mixer_check_p99_ms is zero after "
                            f"{n_checks} served checks")
        if "mixer_runtime_resolve_count" not in text:
            failures.append("prometheus_client registry missing from "
                            "the merged exposition")
    finally:
        intro.close()
        srv.close()
        tracing.shutdown()

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"introspect smoke ok: {len(REQUIRED_STAGES)} stages + "
              f"{len(REQUIRED_GAUGES)} gauges live after "
              f"{n_checks} checks")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=32)
    ap.add_argument("--checks", type=int, default=100)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.checks))
