"""Sweep BatchCheck batch sizes / concurrency against the real device
transport to pick the served-bench knobs."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    import bench  # noqa: F401  (jax cache config)
    from istio_tpu.api.grpc_server import MixerAioGrpcServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import perf, workloads

    store = workloads.make_store(1000)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.002, max_batch=2048, pipeline=2,
        buckets=(256, 1024, 2048),
        default_manifest=workloads.MESH_MANIFEST))
    plan = srv.controller.dispatcher.fused
    if plan is not None:
        plan.prewarm((256, 1024, 2048))
    g = MixerAioGrpcServer(srv)
    port = g.start()
    dicts = workloads.make_request_dicts(512)
    try:
        for bsz, conc in ((1024, 2), (1024, 3), (2048, 2), (2048, 3)):
            payloads = perf.make_batch_check_payloads(dicts, bsz)
            t0 = time.time()
            rep = perf.run_load(
                f"127.0.0.1:{port}", payloads, n_record=40,
                n_procs=1, concurrency=conc, warmup_s=2.0,
                method="/istio.mixer.v1.Mixer/BatchCheck",
                checks_per_payload=bsz)
            print(f"bsz={bsz} conc={conc}: "
                  f"{rep.checks_per_sec:.0f} checks/s "
                  f"rpc_p50={rep.p50_ms:.0f}ms "
                  f"rpc_p99={rep.p99_ms:.0f}ms err={rep.n_errors} "
                  f"wall={time.time() - t0:.0f}s", flush=True)
    finally:
        g.stop()
        srv.close()
