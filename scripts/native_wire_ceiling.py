"""Measure the native wire's loopback ceiling: httpd.cpp in ECHO mode
(fixed OK CheckResponse written in C++, no engine) driven by the C++
h2load client. This is the counterpart of scripts/grpc_ceiling.py for
the native front — the number that bounds served_native throughput on
this box (1 core shared by client + server)."""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    from istio_tpu.api.native_server import start_echo_server
    from istio_tpu.testing import perf, workloads

    port, stop = start_echo_server()
    payloads = perf.make_check_payloads(workloads.make_request_dicts(64))
    try:
        for depth in (1, 64, 256):
            rep = perf.run_h2load(port, payloads, 20000, depth, 1.0)
            print(json.dumps({"mode": "echo", **rep}))
    finally:
        stop()


if __name__ == "__main__":
    main()
