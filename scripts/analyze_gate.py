"""Analyzer CI gate: seeded faults MUST be caught, clean configs MUST
be silent.

Drives istio_tpu/analysis over two corpora (tests run main()
in-process via tests/test_analyze_smoke.py; standalone under
JAX_PLATFORMS=cpu):

  CLEAN — the golden configs (workloads.make_store snapshot, a seeded
  clean rule world, a crafted clean route table): ANY finding fails
  the gate (a noisy analyzer cannot gate admission).

  SEEDED FAULTS — testing/corpus.make_analyzer_faults plants one
  defect per class at an rng-chosen position: a fully-shadowed rule,
  an ALLOW/DENY overlap, a type error, an NFA state-budget blow-up,
  plus make_plane_divergence_pairs' Pilot/Mixer divergence. Every
  fault must surface as an ERROR finding naming the planted rule;
  shadow/conflict/divergence findings must carry an oracle-confirmed
  witness. The same faults are then replayed through the OTHER two
  surfaces: `mixs analyze` must exit non-zero on a faulted FsStore
  (and zero on the clean one), and the kube admission hook must reject
  the faulted rule objects at CREATE.

Usage: JAX_PLATFORMS=cpu python scripts/analyze_gate.py [--seed N]
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _clean_leg(seed: int, failures: list[str]) -> None:
    from istio_tpu.analysis import (analyze_route_table, analyze_rules,
                                    analyze_snapshot)
    from istio_tpu.expr.checker import AttributeDescriptorFinder
    from istio_tpu.pilot.model import Config, ConfigMeta, Port, Service
    from istio_tpu.pilot.route_nfa import RouteTable
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.testing import corpus, workloads

    # golden snapshot (the serving benches' config shape)
    snap = SnapshotBuilder(workloads.MESH_MANIFEST).build(
        workloads.make_store(45))
    rep = analyze_snapshot(snap)
    if rep.findings:
        failures.append(f"clean make_store snapshot raised "
                        f"{[f.code for f in rep.findings]}")

    # seeded clean rule world
    finder = AttributeDescriptorFinder(corpus.ANALYZER_MANIFEST)
    rules = corpus.make_analyzer_clean_rules(seed)
    rep = analyze_rules(rules, finder,
                        deny_idx=tuple(range(len(rules))),
                        check_totality=False)
    if rep.findings:
        failures.append(f"clean seeded rules raised "
                        f"{[f.code for f in rep.findings]}")

    # crafted clean route table: distinct hosts, one rule each
    services = [Service(hostname=f"svc{i}.default.svc.cluster.local",
                        address=f"10.9.0.{i + 1}",
                        ports=(Port("http", 9080, "HTTP"),))
                for i in range(4)]
    rules_by_host = {
        s.hostname: [Config(ConfigMeta(type="route-rule", name=f"rr{i}",
                                       namespace="default"),
                            {"destination": {"name": f"svc{i}"},
                             "precedence": 1,
                             "match": {"request": {"headers": {
                                 "uri": {"prefix": f"/api/v{i}/"}}}},
                             "route": [{"labels": {"version": "v1"}}]})]
        for i, s in enumerate(services)}
    rep = analyze_route_table(RouteTable(services, rules_by_host))
    if rep.findings:
        failures.append(f"clean route table raised "
                        f"{[f.code for f in rep.findings]}")


def _fault_leg(seed: int, failures: list[str]) -> None:
    from istio_tpu.analysis import analyze_rules, check_plane_pairs
    from istio_tpu.attribute.bag import DictBag
    from istio_tpu.expr.checker import AttributeDescriptorFinder
    from istio_tpu.expr.oracle import OracleProgram
    from istio_tpu.compiler.ruleset import _rule_ast
    from istio_tpu.testing import corpus

    finder = AttributeDescriptorFinder(corpus.ANALYZER_MANIFEST)
    witness_codes = ("shadowed-rule", "allow-deny-conflict")
    for case in corpus.make_analyzer_faults(seed):
        rep = analyze_rules(case.rules, finder,
                            deny_idx=case.deny_idx,
                            allow_idx=case.allow_idx,
                            check_totality=False)
        hits = [f for f in rep.errors if f.code == case.kind
                and any(case.fault_rule in r for r in f.rules)]
        if not hits:
            failures.append(
                f"seeded {case.kind} ({case.description}) went "
                f"UNDETECTED: report codes {sorted(rep.codes())}")
            continue
        stray = [f for f in rep.errors
                 if not any(case.fault_rule in r for r in f.rules)]
        if stray:
            failures.append(f"{case.kind} world raised stray errors "
                            f"{[f.code for f in stray]}")
        if case.kind not in witness_codes:
            continue
        f = hits[0]
        if f.witness is None or not f.confirmed:
            failures.append(f"{case.kind} finding shipped no "
                            f"confirmed witness")
            continue
        # independent oracle replay (the property the findings claim)
        by_name = {r.name: r for r in case.rules}
        for rname in f.rules:
            rule = by_name[rname]
            try:
                v = OracleProgram.from_ast(
                    _rule_ast(rule), finder).evaluate(
                        DictBag(dict(f.witness)))
            except Exception as exc:
                failures.append(f"{case.kind} witness errors on "
                                f"{rname}: {exc}")
                break
            if v is not True:
                failures.append(f"{case.kind} witness does not match "
                                f"{rname}")
                break

    pairs, diverge_at = corpus.make_plane_divergence_pairs(seed)
    fs = check_plane_pairs(pairs, finder)
    div = [f for f in fs if f.code == "plane-divergence"]
    if len(div) != 1 or f"route{diverge_at}" not in div[0].rules:
        failures.append(f"plane divergence at pair {diverge_at} not "
                        f"isolated: {[f.to_dict() for f in fs]}")
    elif div[0].witness is None or not div[0].confirmed:
        failures.append("plane-divergence finding shipped no witness")


def _store_dir(tmp: str, name: str, rules, allow_rules=()) -> str:
    """Write a rule world as an FsStore directory (denyall on every
    rule; whitelist on `allow_rules`)."""
    import yaml

    root = os.path.join(tmp, name)
    os.makedirs(root, exist_ok=True)
    docs = [
        {"kind": "handler",
         "metadata": {"name": "denyall", "namespace": "istio-system"},
         "spec": {"adapter": "denier", "params": {}}},
        {"kind": "handler",
         "metadata": {"name": "wl", "namespace": "istio-system"},
         "spec": {"adapter": "list",
                  "params": {"overrides": ["ns1"],
                             "blacklist": False}}},
    ]
    allow = set(allow_rules)
    for r in rules:
        handler = "wl.istio-system" if r.name in allow \
            else "denyall.istio-system"
        docs.append({"kind": "rule",
                     "metadata": {"name": r.name,
                                  "namespace": r.namespace
                                  or "istio-system"},
                     "spec": {"match": r.match,
                              "actions": [{"handler": handler,
                                           "instances": []}]}})
    with open(os.path.join(root, "world.yaml"), "w",
              encoding="utf-8") as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)
    return root


def _cli_leg(seed: int, failures: list[str]) -> None:
    import contextlib
    import io

    from istio_tpu.cmd.__main__ import main as cli_main
    from istio_tpu.testing import corpus

    def run(argv) -> int:
        with contextlib.redirect_stdout(io.StringIO()):
            return cli_main(argv)

    with tempfile.TemporaryDirectory() as tmp:
        clean = _store_dir(tmp, "clean",
                           corpus.make_analyzer_clean_rules(seed))
        rc = run(["analyze", "--config-store", clean, "--json"])
        if rc != 0:
            failures.append(f"`mixs analyze` exited {rc} on the clean "
                            f"store")
        for case in corpus.make_analyzer_faults(seed):
            root = _store_dir(
                tmp, case.kind, case.rules,
                allow_rules=[case.rules[i].name
                             for i in case.allow_idx])
            rc = run(["analyze", "--config-store", root, "--json"])
            if rc == 0:
                failures.append(f"`mixs analyze` exited 0 on the "
                                f"seeded {case.kind} store")


def _admission_leg(seed: int, failures: list[str]) -> None:
    from istio_tpu.kube.admission import (register_analysis_admission,
                                          register_istio_admission)
    from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster
    from istio_tpu.testing import corpus

    def obj(kind, name, ns, spec):
        return {"kind": kind,
                "metadata": {"name": name, "namespace": ns},
                "spec": spec}

    for case in corpus.make_analyzer_faults(seed):
        cluster = FakeKubeCluster()
        register_istio_admission(cluster)
        register_analysis_admission(
            cluster, default_manifest=corpus.ANALYZER_MANIFEST)
        cluster.create(obj("handler", "denyall", "istio-system",
                           {"adapter": "denier", "params": {}}))
        cluster.create(obj("handler", "wl", "istio-system",
                           {"adapter": "list",
                            "params": {"overrides": ["ns1"],
                                       "blacklist": False}}))
        allow = {case.rules[i].name for i in case.allow_idx}
        *setup, fault = case.rules
        try:
            for r in setup:
                handler = "wl.istio-system" if r.name in allow \
                    else "denyall.istio-system"
                cluster.create(obj(
                    "rule", r.name, r.namespace or "istio-system",
                    {"match": r.match,
                     "actions": [{"handler": handler,
                                  "instances": []}]}))
        except AdmissionDenied as exc:
            failures.append(f"{case.kind}: clean setup rule rejected "
                            f"at admission: {exc}")
            continue
        try:
            handler = "wl.istio-system" if fault.name in allow \
                else "denyall.istio-system"
            cluster.create(obj(
                "rule", fault.name, fault.namespace or "istio-system",
                {"match": fault.match,
                 "actions": [{"handler": handler, "instances": []}]}))
            failures.append(f"{case.kind}: admission ADMITTED the "
                            f"seeded fault rule {fault.name}")
        except AdmissionDenied:
            pass


def main(seed: int = 20260803) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failures: list[str] = []
    _clean_leg(seed, failures)
    _fault_leg(seed, failures)
    _cli_leg(seed, failures)
    _admission_leg(seed, failures)
    for f in failures:
        print(f"analyze_gate: FAIL: {f}")
    if not failures:
        print(f"analyze_gate: ok (seed={seed}: 4 fault classes + "
              f"plane divergence detected on every surface, clean "
              f"configs silent)")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=20260803,
                    help="reproducible corpus seed")
    args = ap.parse_args()
    sys.exit(main(seed=args.seed))
