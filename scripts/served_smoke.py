"""Smoke-run the served bench section (optionally under a CPU hog) to
prove the completion-counted rig cannot report an empty window, and to
tune serving knobs against the real device transport.

Usage: /opt/venv/bin/python scripts/served_smoke.py \
           [--hog] [--rules N] [--conc N] [--n N]
"""
import argparse
import json
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _hog(stop_t: float) -> None:
    x = 1.0
    while time.time() < stop_t:
        x = x * 1.0000001 + 1e-9


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--hog", action="store_true")
    ap.add_argument("--rules", type=int, default=200)
    ap.add_argument("--conc", type=int, default=0,
                    help="override client concurrency")
    ap.add_argument("--n", type=int, default=0,
                    help="override n_record")
    ap.add_argument("--tpu-shape", action="store_true",
                    help="use the on_tpu knob values")
    args = ap.parse_args()

    import bench
    from istio_tpu.testing import perf

    if args.conc or args.n:
        # patch the knobs run_load is called with
        orig = perf.run_load

        def patched(target, payloads, n_record=2000, n_procs=4,
                    concurrency=32, warmup_s=2.0, **kw):
            if kw.get("method", "").endswith("BatchCheck"):
                # batched phase: knobs are its own; pass through
                return orig(target, payloads, n_record=n_record,
                            n_procs=n_procs, concurrency=concurrency,
                            warmup_s=warmup_s, **kw)
            return orig(target, payloads,
                        n_record=args.n or n_record,
                        n_procs=n_procs,
                        concurrency=args.conc or concurrency,
                        warmup_s=warmup_s, **kw)
        perf.run_load = patched

    hog_proc = None
    if args.hog:
        hog_proc = multiprocessing.get_context("spawn").Process(
            target=_hog, args=(time.time() + 600,), daemon=True)
        hog_proc.start()
        print("cpu hog running", file=sys.stderr)
    t0 = time.time()
    out = bench._served_bench(n_rules=args.rules, on_tpu=args.tpu_shape)
    out["smoke_wall_s"] = round(time.time() - t0, 1)
    if hog_proc is not None:
        hog_proc.terminate()
    print(json.dumps(out, indent=1))
