"""Tier-1 gate for the secure serving plane (istio_tpu/secure) — the
CI proof that workload identity actually fronts the device-compiled
RBAC plane. Boots a real CA (CSR gRPC service), obtains serving and
workload certs over the wire, serves strict-mTLS traffic through the
gRPC front, and FAILS (nonzero exit) unless:

  1. IDENTITY FEEDS THE DEVICE: every strict-mTLS Check carries the
     VERIFIED peer SPIFFE identity as `source.user` +
     `connection.mtls`, the compiled RBAC rules evaluate it on-device,
     and the wire verdicts match the SnapshotOracle over the
     identity-folded bags EXACTLY — including a spoof attempt (the
     wire-claimed source.user is overridden by the handshake identity).
  2. THE BOUNDARY IS TYPED: a CA-signed cert with no SPIFFE URI SAN
     answers UNAUTHENTICATED (google.rpc 16, never INTERNAL); a
     cert-less peer never completes the strict handshake (UNAVAILABLE
     at the client, nothing reaches admission).
  3. ROTATION DROPS NOTHING: the serving identity rotates (CSR flow,
     maintenance-lane ordering: sign -> swap ServingCerts -> revoke
     identity grants) under live closed-loop traffic — zero dropped
     requests, post-rotation handshakes serve against the new
     generation, the forensics timeline carries identity_rotate
     events, and the zero-shaped mixer_identity_* counters moved.

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_mtls_smoke.py). Needs a PKI backend — `cryptography` or
the openssl CLI (secure/backend.py); exits 0 with a notice when the
rig has neither.

Usage: JAX_PLATFORMS=cpu python scripts/mtls_smoke.py [--checks N]
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WEB = "spiffe://cluster.local/ns/default/sa/web"
DB = "spiffe://cluster.local/ns/default/sa/db"
MIXER = "spiffe://cluster.local/ns/istio-system/sa/istio-mixer"
PERMISSION_DENIED = 7


def _identity_store(db_identity: str):
    """RBAC plane keyed on the VERIFIED principal: payments is closed
    to the db workload, and anything that somehow lacks connection
    identity is denied outright (defense in depth under the strict
    handshake)."""
    from istio_tpu.runtime import MemStore
    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier",
        "params": {"status_message": "rbac: principal not allowed"}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "rbac-require-mtls"), {
        "match": '(connection.mtls | false) == false',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    s.set(("rule", "istio-system", "rbac-db-no-payments"), {
        "match": f'(source.user | "") == "{db_identity}" && '
                 'destination.service == '
                 '"payments.default.svc.cluster.local"',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    return s


def main(n_checks: int = 24, rotations: int = 3,
         workers: int = 3, rotate_window_s: float = 0.35) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from istio_tpu.secure.backend import available_backends
    if not available_backends():
        print("mtls smoke: no PKI backend on this rig (cryptography "
              "or the openssl CLI) — nothing to gate")
        return 0

    import grpc

    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime import forensics, monitor
    from istio_tpu.secure.identity import WorkloadIdentity
    from istio_tpu.secure.mtls import ServingCerts
    from istio_tpu.security import IstioCA, pki
    from istio_tpu.security.ca_service import (
        CAClient, CAGrpcServer, allow_any_identity_authorizer)
    from istio_tpu.sharding import oracle_check_statuses
    from istio_tpu.utils import tracing

    failures: list[str] = []
    base_identity = monitor.identity_counters()

    # ---- the secure plane, end to end over the wire ----------------
    ca = IstioCA.new_self_signed({})
    root = ca.get_root_certificate()
    ca_srv = CAGrpcServer(ca, lambda ct, cred: "smoke",
                          authorizer=allow_any_identity_authorizer,
                          insecure_port=True)
    ca_client = CAClient(f"127.0.0.1:{ca_srv.start()}")

    def obtain(identity: str, dns=()) -> WorkloadIdentity:
        wi = WorkloadIdentity(ca_client, identity, ttl_minutes=5,
                              dns_names=dns)
        wi.ensure()
        return wi

    wi_srv = obtain(MIXER, dns=("mixer.local",))
    key_pem, cert_pem, root_pem = wi_srv.bundle()
    certs = ServingCerts(key_pem, cert_pem, root_pem)
    srv = RuntimeServer(_identity_store(DB), ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        check_grants=True, mtls="strict", mtls_identity=MIXER))
    # the PR 11 rotation ordering as subscriptions: sign -> swap the
    # serving bundle -> revoke grants keyed to the rotated identity
    wi_srv.subscribe(lambda b: certs.rotate(b[0], b[1], b[2]))
    wi_srv.subscribe(
        lambda b: srv.grants.on_identity_rotate(wi_srv.identity))
    front = MixerGrpcServer(srv, tls=certs, mtls_mode="strict")
    port = front.start()

    def connect(wi: WorkloadIdentity | None,
                key: bytes = b"", cert: bytes = b"") -> MixerClient:
        if wi is not None:
            key, cert, _root = wi.bundle()
        return MixerClient(f"127.0.0.1:{port}",
                           enable_check_cache=False,
                           root_cert_pem=root, key_pem=key or None,
                           cert_pem=cert or None,
                           server_name="mixer.local")

    clients: list = []
    try:
        wi_web = obtain(WEB)
        wi_db = obtain(DB)

        # ---- 1. identity-fed RBAC: wire vs oracle, EXACT -----------
        dests = ["payments.default.svc.cluster.local",
                 "catalog.default.svc.cluster.local",
                 "ledger.default.svc.cluster.local"]
        wire_codes: list[int] = []
        bags = []
        for wi, ident in ((wi_web, WEB), (wi_db, DB)):
            cl = connect(wi)
            clients.append(cl)
            for i in range(n_checks // 2):
                d = {"destination.service": dests[i % len(dests)],
                     "request.path": f"/api/{i}"}
                if i % 4 == 1:
                    # spoof attempt: claim the OTHER principal in the
                    # wire attributes — the handshake identity must win
                    d["source.user"] = WEB if ident == DB else DB
                resp = cl.check(d)
                wire_codes.append(int(resp.precondition.status.code))
                bags.append(bag_from_mapping({
                    **d, "source.user": ident,
                    "connection.mtls": True}))
        snap = srv.controller.dispatcher.snapshot
        plan = srv.controller.dispatcher.fused
        if plan is None:
            failures.append("no fused plan — RBAC rules not compiled")
        else:
            expected = oracle_check_statuses(snap, plan, bags)
            for i, (want, got) in enumerate(zip(expected, wire_codes)):
                if got != want["status"]:
                    failures.append(
                        f"row {i}: wire status {got} != oracle "
                        f"{want['status']} — identity-fed device "
                        f"verdict diverged")
                    if len(failures) > 8:
                        break
        if PERMISSION_DENIED not in wire_codes:
            failures.append("no deny outcome — the db->payments RBAC "
                            "rule never fired")
        if 0 not in wire_codes:
            failures.append("no ok outcome — RBAC denied everything")

        # ---- 2. typed rejection boundary ---------------------------
        anon_key = pki.generate_key()
        anon_cert = ca.sign(pki.generate_csr(anon_key, None, org="x"))
        noid = connect(None, key=pki.key_to_pem(anon_key),
                       cert=anon_cert)
        clients.append(noid)
        try:
            noid.check({"destination.service": dests[1]})
            failures.append("identity-less cert was served — typed "
                            "UNAUTHENTICATED boundary is gone")
        except grpc.RpcError as exc:
            if exc.code() != grpc.StatusCode.UNAUTHENTICATED:
                failures.append(f"identity-less cert answered "
                                f"{exc.code()}, not UNAUTHENTICATED")
        certless = connect(None)
        clients.append(certless)
        try:
            certless.check({"destination.service": dests[1]})
            failures.append("cert-less peer completed a strict "
                            "handshake")
        except grpc.RpcError as exc:
            if exc.code() != grpc.StatusCode.UNAVAILABLE:
                failures.append(f"cert-less peer answered "
                                f"{exc.code()}, expected handshake "
                                f"refusal (UNAVAILABLE)")

        # ---- 3. rotation under live closed-loop traffic ------------
        stop = threading.Event()
        drops: list[str] = []
        served = [0] * workers

        def closed_loop(k: int) -> None:
            cl = connect(wi_web)
            try:
                while not stop.is_set():
                    try:
                        r = cl.check({"destination.service":
                                      dests[k % len(dests)]})
                        if r.precondition.status.code != 0:
                            drops.append(
                                f"worker {k}: status "
                                f"{r.precondition.status.code}")
                        served[k] += 1
                    except grpc.RpcError as exc:
                        drops.append(f"worker {k}: {exc.code()}")
            finally:
                cl.close()

        threads = [threading.Thread(target=closed_loop, args=(k,),
                                    daemon=True)
                   for k in range(workers)]
        for t in threads:
            t.start()
        gen0 = certs.generation
        for r in range(rotations):
            time.sleep(rotate_window_s)
            wi_srv.rotate()
            # a FRESH connection must handshake against the rotated
            # generation while the old connections keep serving
            fresh = connect(wi_web)
            resp = fresh.check({"destination.service": dests[2]})
            if resp.precondition.status.code != 0:
                failures.append(f"post-rotation {r + 1} check failed: "
                                f"{resp.precondition.status.code}")
            fresh.close()
        time.sleep(rotate_window_s)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if drops:
            failures.append(f"{len(drops)} dropped/denied requests "
                            f"through {rotations} rotations: "
                            f"{drops[:4]}")
        if sum(served) < workers * rotations:
            failures.append(f"closed loop barely ran: {served}")
        if certs.generation != gen0 + rotations:
            failures.append(f"serving generation {certs.generation} "
                            f"!= {gen0 + rotations} after "
                            f"{rotations} rotations")

        # observability: forensics events + zero-shaped counters
        rot_events = forensics.EVENTS.snapshot(kind="identity_rotate")
        n_rot = sum(e["n"] for e in rot_events
                    if e["detail"].get("identity") == MIXER
                    and e["detail"].get("ok"))
        if n_rot < rotations:
            failures.append(f"forensics saw {n_rot} identity_rotate "
                            f"events for {MIXER}, expected "
                            f">= {rotations}")
        cnt = monitor.identity_counters()
        for family in ("events", "unauthenticated_total",
                       "authenticated_checks_total"):
            if family not in cnt:
                failures.append(f"identity counter family {family} "
                                f"missing — zero-shaping broken")
        d_rot = cnt["events"]["rotate"]["ok"] \
            - base_identity["events"]["rotate"]["ok"]
        if d_rot < rotations:
            failures.append(f"mixer_identity_events rotate/ok moved "
                            f"{d_rot}, expected >= {rotations}")
        if cnt["unauthenticated_total"] \
                <= base_identity["unauthenticated_total"]:
            failures.append("typed UNAUTHENTICATED rejection did not "
                            "count")
        # grant fold: the rotated identity's next grant is floored
        ttl, _uses = srv.grants.identity_grant(wi_srv.identity)
        if ttl > srv.grants.ttl_floor_s + 0.5 + rotate_window_s:
            failures.append(f"identity grant TTL {ttl} not floored "
                            f"after rotation")
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        front.stop()
        srv.close()
        ca_client.close()
        ca_srv.stop()
        tracing.shutdown()

    if failures:
        print("mtls smoke FAILURES:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"mtls smoke ok: strict-mTLS identity fed the device RBAC "
          f"plane with EXACT oracle parity over {len(wire_codes)} "
          f"checks (spoofs overridden), typed UNAUTHENTICATED / "
          f"handshake-refusal boundaries held, {rotations} serving "
          f"rotations under closed-loop load dropped 0 of "
          f"{sum(served)} requests")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--checks", type=int, default=24)
    ap.add_argument("--rotations", type=int, default=3)
    ap.add_argument("--workers", type=int, default=3)
    a = ap.parse_args()
    raise SystemExit(main(n_checks=a.checks, rotations=a.rotations,
                          workers=a.workers))
