"""Delta smoke: config churn must be (nearly) free on a sharded
snapshot. Build a seeded fleet snapshot into K namespace banks with a
persistent XLA compilation cache configured, then FAIL (nonzero exit)
unless

  1. a ONE-NAMESPACE constant-only delta republishes by rebuilding
     exactly ONE bank: the other K-1 banks carry across the
     generation as the SAME objects (prewarmed shapes, breaker,
     telemetry bindings intact), the plan keeps every namespace on
     its shard (routing byte-identical), and the rebuild ledger +
     /debug/shards agree on reused-vs-recompiled counts;
  2. the delta actually TOOK EFFECT (a probe request flips from
     deny to allow across the republish) and the sharded path stays
     EXACTLY oracle-parity over the real gRPC front, before and
     after the delta;
  3. a SIMULATED RESTART (a fresh RuntimeServer over the mutated
     store, same process — new jit callables, cold in-memory caches)
     with the warm persistent compilation cache serves WITHOUT
     recompiling unchanged banks: zero XLA cache misses and nonzero
     hits across the whole rebuild, no new artifacts on disk, and
     exact oracle parity again.

The edit is constant-only (a literal swap inside one rule's match) —
the dominant real config churn shape. Compiled programs take their
index tensors as traced ARGUMENTS (compiler/ruleset.py), so such an
edit keeps every HLO bit-identical: even the one recompiled bank's
XLA artifact comes out of the persistent cache, and the whole
republish cost is host-side (plan diff + one bank's trace).

Runnable under JAX_PLATFORMS=cpu; tier-1 invokes main() in-process
(tests/test_delta_smoke.py) at the platform scale from the issue
(100k rules tpu / 4k cpu).

Usage: JAX_PLATFORMS=cpu python scripts/delta_smoke.py \
           [--rules N] [--namespaces N] [--shards K] [--checks N] \
           [--seed N]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _wire_parity(client, srv, dicts, failures, tag,
                 bag_from_mapping, oracle_check_statuses) -> int:
    """Serve `dicts` over the real gRPC front AND in-process, judge
    both against the SnapshotOracle exactly. Returns denies seen."""
    wire_codes = [int(client.check(d).precondition.status.code)
                  for d in dicts]
    bags = [bag_from_mapping(d) for d in dicts]
    local = srv.check_many(bags)
    snap = srv.controller.dispatcher.snapshot
    expected = oracle_check_statuses(
        snap, srv.controller.dispatcher.fused, bags)
    n_deny = 0
    for i, (want, got, code) in enumerate(
            zip(expected, local, wire_codes)):
        if got.status_code != want["status"]:
            failures.append(f"{tag} row {i}: sharded status "
                            f"{got.status_code} != oracle "
                            f"{want['status']}")
        if code != want["status"]:
            failures.append(f"{tag} row {i}: wire status {code} != "
                            f"oracle {want['status']}")
        if got.deny_rule != want["deny_rule"]:
            failures.append(f"{tag} row {i}: deny_rule "
                            f"{got.deny_rule} != oracle "
                            f"{want['deny_rule']}")
        if want["status"] != 0:
            n_deny += 1
        if len(failures) > 16:
            break
    if not n_deny:
        failures.append(f"{tag}: oracle saw zero denies — the "
                        f"traffic no longer exercises deny rules")
    return n_deny


def main(n_rules: int | None = None, n_namespaces: int | None = None,
         shards: int | None = None, n_checks: int = 48,
         seed: int = 7) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import time

    import jax

    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.compiler import cache as compile_cache
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.runtime.store import Event
    from istio_tpu.sharding import oracle_check_statuses
    from istio_tpu.testing import workloads
    from istio_tpu.testing.workloads import _fleet_ns_assignment
    from istio_tpu.utils import tracing

    on_tpu = jax.devices()[0].platform == "tpu"
    n_rules = n_rules or (100_000 if on_tpu else 4_000)
    n_namespaces = n_namespaces or (512 if on_tpu else 64)
    shards = shards or (8 if on_tpu else 4)

    failures: list[str] = []
    cache_dir = tempfile.mkdtemp(prefix="delta_smoke_jax_cache_")
    prev_cache_dir = jax.config.jax_compilation_cache_dir
    prev_min_s = jax.config.jax_persistent_cache_min_compile_time_secs
    compile_cache.install_event_counters()
    srv = srv2 = intro = g = client = None
    try:
        store = workloads.make_fleet_store(n_rules, n_namespaces,
                                           seed)
        args = ServerArgs(
            batch_window_s=0.0005, max_batch=16, buckets=(16,),
            shards=shards, replicas=1,
            rule_telemetry=False, initial_prewarm=False,
            default_manifest=workloads.MESH_MANIFEST,
            jax_compile_cache_dir=cache_dir)
        t0 = time.perf_counter()
        srv = RuntimeServer(store, args)
        build_s = time.perf_counter() - t0

        state = srv._sharded
        if state["mode"] != "sharded":
            failures.append(f"expected sharded mode, got "
                            f"{state['mode']} "
                            f"({state['fallback_reason']})")
        st = dict(srv._rebuild_status)
        if st["rebuilds"] != 1 or st["banks_reused"] != 0 \
                or st["banks_recompiled"] != shards \
                or st["last_error"] is not None:
            failures.append(f"first-build ledger wrong: {st}")
        plan0 = state["plan"]
        banks0 = {b.shard_id: b for b in state["banks"]}

        # -- the probe rule: denier action + a source-namespace
        #    literal we can constant-swap (i%3==0 picks the denier
        #    action in make_fleet_store, i%4<2 the != conjunct) -----
        probe_i = next(i for i in range(0, n_rules, 12)
                       if i % 3 == 0 and i % 4 < 2)
        ns_of = _fleet_ns_assignment(n_rules, n_namespaces, seed)
        probe_ns = f"ns{int(ns_of[probe_i])}"
        probe = {
            "destination.service":
                f"svc{probe_i}.{probe_ns}.svc.cluster.local",
            "source.namespace": "probe-team",
            "source.user": "sidecar-probe",
            "request.method": "GET",
            "connection.mtls": True,
            "request.path": "/probe",
        }

        intro = IntrospectServer(runtime=srv)
        intro_port = intro.start()
        g = MixerGrpcServer(runtime=srv)
        grpc_port = g.start()
        client = MixerClient(f"127.0.0.1:{grpc_port}",
                             enable_check_cache=False)

        dicts = workloads.make_fleet_traffic(
            n_checks, n_rules, n_namespaces, seed)
        _wire_parity(client, srv, dicts, failures, "pre-delta",
                     bag_from_mapping, oracle_check_statuses)
        pre_code = int(client.check(probe)
                       .precondition.status.code)
        if pre_code != 7:
            failures.append(f"probe rule fleet{probe_i} should deny "
                            f"(7) pre-delta, got {pre_code}")

        # -- ONE-namespace constant-only delta ----------------------
        key = ("rule", probe_ns, f"fleet{probe_i}")
        spec = dict(store.get(key))
        locked = f'"locked{probe_i % 5}"'
        if locked not in spec["match"]:
            failures.append(f"probe rule match has no {locked}: "
                            f"{spec['match']}")
        spec["match"] = spec["match"].replace(locked, '"probe-team"')
        # quiet apply + one explicit rebuild: the republish under
        # test is deterministic, not racing the debounce timer
        store.apply_events([Event(key, spec)], notify=False)
        t0 = time.perf_counter()
        srv.controller.rebuild()
        delta_s = time.perf_counter() - t0

        state = srv._sharded
        st = dict(srv._rebuild_status)
        delta = state["delta"]
        want_shard = plan0.shard_of(probe_ns)
        if st["banks_reused"] != shards - 1 \
                or st["banks_recompiled"] != 1:
            failures.append(f"delta ledger: expected {shards - 1} "
                            f"reused / 1 recompiled, got {st}")
        if delta["recompiled"] != [want_shard]:
            failures.append(f"recompiled banks {delta['recompiled']}"
                            f" != [{want_shard}] (the probe ns's "
                            f"shard)")
        plan1 = state["plan"]
        if plan1.ns_to_shard != plan0.ns_to_shard:
            moved = {ns for ns in set(plan0.ns_to_shard)
                     | set(plan1.ns_to_shard)
                     if plan0.ns_to_shard.get(ns)
                     != plan1.ns_to_shard.get(ns)}
            failures.append(f"plan moved namespaces under a pure "
                            f"edit: {sorted(moved)[:8]}")
        # carried banks are shallow copies sharing the COMPILED
        # artifact (dispatcher + fused plan + checker) — the old
        # generation keeps its own index map while batches drain
        carried = {b.shard_id: b for b in state["banks"]}
        for k in range(shards):
            if k == want_shard:
                if carried[k].dispatcher is banks0[k].dispatcher:
                    failures.append(f"bank {k} should have been "
                                    f"recompiled, compiled artifact "
                                    f"carried")
            else:
                if carried[k].dispatcher is not banks0[k].dispatcher:
                    failures.append(f"bank {k} was rebuilt — expected "
                                    f"the carried compiled artifact")
                if carried[k].checker is not banks0[k].checker:
                    failures.append(f"bank {k} breaker/checker did "
                                    f"not carry across the delta")

        post_code = int(client.check(probe)
                        .precondition.status.code)
        if post_code != 0:
            failures.append(f"probe should flip to allow (0) after "
                            f"the delta, got {post_code}")
        _wire_parity(client, srv, dicts, failures, "post-delta",
                     bag_from_mapping, oracle_check_statuses)

        # -- /debug/shards agreement --------------------------------
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro_port}/debug/shards",
                timeout=30) as r:
            view = json.loads(r.read().decode())
        vre = view.get("rebuild", {})
        if vre.get("banks_reused") != shards - 1 \
                or vre.get("banks_recompiled") != 1:
            failures.append(f"/debug/shards rebuild ledger disagrees:"
                            f" {vre}")
        if view.get("delta", {}).get("recompiled") != [want_shard]:
            failures.append(f"/debug/shards delta block disagrees: "
                            f"{view.get('delta')}")
        if "xla_cache_events" not in view.get("compile_cache", {}):
            failures.append(f"/debug/shards compile_cache block "
                            f"missing: {view.get('compile_cache')}")

        client.close(); client = None
        g.stop(); g = None
        intro.close(); intro = None
        srv.close(); srv = None

        # -- simulated restart with the warm persistent cache -------
        entries0 = compile_cache.persistent_cache_entries(cache_dir)
        if entries0 <= 0:
            failures.append("persistent cache is empty after the "
                            "first server's lifetime — nothing was "
                            "cached")
        ev0 = compile_cache.cache_event_counts()
        t0 = time.perf_counter()
        srv2 = RuntimeServer(store, args)
        restart_s = time.perf_counter() - t0
        ev1 = compile_cache.cache_event_counts()
        new_misses = ev1["misses"] - ev0["misses"]
        new_hits = ev1["hits"] - ev0["hits"]
        if new_misses != 0:
            failures.append(f"restart recompiled {new_misses} XLA "
                            f"programs — the warm persistent cache "
                            f"should have served every unchanged "
                            f"bank ({new_hits} hits)")
        if new_hits <= 0:
            failures.append("restart produced zero persistent-cache "
                            "hits — the cache is not being consulted")
        entries1 = compile_cache.persistent_cache_entries(cache_dir)
        if entries1 != entries0:
            failures.append(f"restart grew the cache "
                            f"{entries0}->{entries1} — new artifacts "
                            f"mean recompiles happened")
        bags = [bag_from_mapping(d) for d in dicts]
        local = srv2.check_many(bags)
        snap2 = srv2.controller.dispatcher.snapshot
        expected = oracle_check_statuses(
            snap2, srv2.controller.dispatcher.fused, bags)
        for i, (want, got) in enumerate(zip(expected, local)):
            if got.status_code != want["status"] \
                    or got.deny_rule != want["deny_rule"]:
                failures.append(
                    f"restart row {i}: ({got.status_code}, "
                    f"{got.deny_rule}) != oracle ({want['status']}, "
                    f"{want['deny_rule']})")
                if len(failures) > 16:
                    break
    finally:
        for closer in (client, g, intro):
            try:
                if closer is not None:
                    (closer.close if not hasattr(closer, "stop")
                     else closer.stop)()
            except Exception:
                pass
        for s in (srv, srv2):
            try:
                if s is not None:
                    s.close()
            except Exception:
                pass
        tracing.shutdown()
        # leave jax's persistent-cache config the way we found it
        # BEFORE deleting the tmpdir (later compiles in this process
        # must not write into a missing directory)
        try:
            jax.config.update("jax_compilation_cache_dir",
                              prev_cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                prev_min_s)
            compile_cache.reset_backend_cache_state()
        except Exception:
            pass
        shutil.rmtree(cache_dir, ignore_errors=True)

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"delta smoke ok: {n_rules} rules / {n_namespaces} ns "
              f"-> {shards} shards; initial build {build_s:.1f}s, "
              f"one-namespace delta republish {delta_s:.2f}s "
              f"reusing {shards - 1}/{shards} banks (compiled "
              f"artifacts + breakers carried, stable plan, EXACT "
              f"gRPC oracle parity, probe deny->allow flip "
              f"observed), warm restart {restart_s:.1f}s with "
              f"{entries0} cached XLA artifacts, 0 misses / "
              f"{new_hits} hits, parity exact")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rules", type=int, default=None)
    ap.add_argument("--namespaces", type=int, default=None)
    ap.add_argument("--shards", type=int, default=None)
    ap.add_argument("--checks", type=int, default=48)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    sys.exit(main(args.rules, args.namespaces, args.shards,
                  args.checks, args.seed))
